"""Model execution for serving: prefill + paged decode.

Reference analog: the vLLM engine internals the reference only *places*
(vllm_engine.py:222, vllm_models.py:117-168). Here the engine is native:
the KV cache is a paged pool `(layers, num_blocks, block_size, kv_heads,
head_dim)`; block tables map each sequence's logical positions onto pool
blocks; decode attention gathers pages (jnp reference impl; the Pallas
ragged-paged-attention kernel drops into `paged_attention` for TPU decode).

Shapes are static per (batch-bucket, max-blocks) so XLA compiles a small,
reusable set of programs — no dynamic shapes in the hot loop.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.models import llama as llama_mod
from ray_tpu.ops.attention import NEG_INF, mha_reference
from ray_tpu.ops.layers import apply_rope, rms_norm, rope_frequencies, swiglu


def init_kv_cache(config: llama_mod.LlamaConfig, num_blocks: int,
                  block_size: int) -> Dict[str, jax.Array]:
    shape = (config.n_layers, num_blocks, block_size, config.n_kv_heads,
             config.head_dim)
    return {"k": jnp.zeros(shape, dtype=config.dtype),
            "v": jnp.zeros(shape, dtype=config.dtype)}


def _write_kv(cache_layer_k, cache_layer_v, k, v, block_ids, offsets):
    """Scatter new kv rows into the paged pool.

    cache_layer_*: (num_blocks, bs, K, hd); k/v: (n_tokens, K, hd);
    block_ids/offsets: (n_tokens,).
    """
    return (cache_layer_k.at[block_ids, offsets].set(k),
            cache_layer_v.at[block_ids, offsets].set(v))


def paged_attention(q, cache_k, cache_v, block_tables, seq_lens, *,
                    block_size: int, scale: float):
    """Decode attention over paged KV.

    q: (b, H, hd) one query token per sequence.
    cache_k/v: (num_blocks, bs, K, hd) for ONE layer.
    block_tables: (b, max_blocks) int32; seq_lens: (b,) lengths INCLUDING the
    current token (whose kv must already be written).
    Returns (b, H, hd).
    """
    b, H, hd = q.shape
    K = cache_k.shape[2]
    max_blocks = block_tables.shape[1]
    max_ctx = max_blocks * block_size
    # Gather pages: (b, max_blocks, bs, K, hd) -> (b, max_ctx, K, hd)
    k = cache_k[block_tables].reshape(b, max_ctx, K, hd)
    v = cache_v[block_tables].reshape(b, max_ctx, K, hd)
    if K != H:
        rep = H // K
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    logits = jnp.einsum("bhd,bkhd->bhk", q, k,
                        preferred_element_type=jnp.float32) * scale
    pos = jnp.arange(max_ctx)[None, :]
    mask = pos < seq_lens[:, None]
    logits = jnp.where(mask[:, None, :], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhk,bkhd->bhd", probs, v)


class ModelRunner:
    """Jit-compiled prefill and decode over a paged cache."""

    def __init__(self, config: llama_mod.LlamaConfig, params,
                 num_blocks: int, block_size: int = 16):
        self.config = config
        self.params = params
        self.block_size = block_size
        self.num_blocks = num_blocks
        self.cache = init_kv_cache(config, num_blocks, block_size)
        self.cos, self.sin = rope_frequencies(
            config.head_dim, config.max_seq, config.rope_theta)
        self._prefill_jit = jax.jit(self._prefill, donate_argnums=(1,))
        self._decode_jit = jax.jit(self._decode, donate_argnums=(1,))

    # ---- prefill ---------------------------------------------------------

    def _prefill(self, tokens, cache, block_table):
        """tokens: (1, s); block_table: (max_blocks,). Returns (logits_last,
        cache) with the prompt's kv written into the pool."""
        config = self.config
        p = self.params
        s = tokens.shape[1]
        x = p["embed"][tokens].astype(config.dtype)
        positions = jnp.arange(s)
        block_ids = block_table[positions // self.block_size]
        offsets = positions % self.block_size

        def layer_step(carry, layer_params_and_idx):
            x, cache_k, cache_v = carry
            lp, li = layer_params_and_idx
            h = rms_norm(x, lp["attn_norm"], config.norm_eps)
            b, s, d = x.shape
            H, K, hd = config.n_heads, config.n_kv_heads, config.head_dim
            q = (h @ lp["wq"]).reshape(b, s, H, hd)
            k = (h @ lp["wk"]).reshape(b, s, K, hd)
            v = (h @ lp["wv"]).reshape(b, s, K, hd)
            q = apply_rope(q, self.cos, self.sin)
            k = apply_rope(k, self.cos, self.sin)
            cache_k = cache_k.at[li, block_ids, offsets].set(k[0])
            cache_v = cache_v.at[li, block_ids, offsets].set(v[0])
            attn = mha_reference(q, k, v, causal=True)
            x = x + (attn.reshape(b, s, H * hd) @ lp["wo"])
            h = rms_norm(x, lp["mlp_norm"], config.norm_eps)
            x = x + (swiglu(h @ lp["w_gate"], h @ lp["w_up"]) @ lp["w_down"])
            return (x, cache_k, cache_v), None

        layer_indices = jnp.arange(config.n_layers)
        (x, ck, cv), _ = jax.lax.scan(
            layer_step, (x, cache["k"], cache["v"]),
            (p["layers"], layer_indices))
        x = rms_norm(x, p["final_norm"], config.norm_eps)
        logits = (x[:, -1, :] @ p["lm_head"].astype(config.dtype)).astype(
            jnp.float32)
        return logits, {"k": ck, "v": cv}

    def prefill(self, tokens: jax.Array, block_table) -> jax.Array:
        logits, self.cache = self._prefill_jit(tokens, self.cache, block_table)
        return logits

    # ---- decode ----------------------------------------------------------

    def _decode(self, tokens, cache, block_tables, positions, seq_lens):
        """tokens: (b,) last sampled token per seq; positions: (b,) where the
        new token goes; seq_lens: (b,) lengths AFTER this token."""
        config = self.config
        p = self.params
        b = tokens.shape[0]
        H, K, hd = config.n_heads, config.n_kv_heads, config.head_dim
        scale = 1.0 / math.sqrt(hd)
        x = p["embed"][tokens].astype(config.dtype)[:, None, :]  # (b,1,d)
        block_ids = jnp.take_along_axis(
            block_tables, (positions // self.block_size)[:, None], axis=1)[:, 0]
        offsets = positions % self.block_size

        def layer_step(carry, layer_params_and_idx):
            x, cache_k, cache_v = carry
            lp, li = layer_params_and_idx
            h = rms_norm(x, lp["attn_norm"], config.norm_eps)
            q = (h @ lp["wq"]).reshape(b, 1, H, hd)
            k = (h @ lp["wk"]).reshape(b, 1, K, hd)
            v = (h @ lp["wv"]).reshape(b, 1, K, hd)
            q = apply_rope(q, self.cos, self.sin, positions[:, None])
            k = apply_rope(k, self.cos, self.sin, positions[:, None])
            cache_k = cache_k.at[li, block_ids, offsets].set(k[:, 0])
            cache_v = cache_v.at[li, block_ids, offsets].set(v[:, 0])
            attn = paged_attention(q[:, 0], cache_k[li], cache_v[li],
                                   block_tables, seq_lens,
                                   block_size=self.block_size, scale=scale)
            x = x + (attn.reshape(b, 1, H * hd) @ lp["wo"])
            h = rms_norm(x, lp["mlp_norm"], config.norm_eps)
            x = x + (swiglu(h @ lp["w_gate"], h @ lp["w_up"]) @ lp["w_down"])
            return (x, cache_k, cache_v), None

        layer_indices = jnp.arange(config.n_layers)
        (x, ck, cv), _ = jax.lax.scan(
            layer_step, (x, cache["k"], cache["v"]),
            (p["layers"], layer_indices))
        x = rms_norm(x, p["final_norm"], config.norm_eps)
        logits = (x[:, 0, :] @ p["lm_head"].astype(config.dtype)).astype(
            jnp.float32)
        return logits, {"k": ck, "v": cv}

    def decode(self, tokens, block_tables, positions, seq_lens) -> jax.Array:
        logits, self.cache = self._decode_jit(
            tokens, self.cache, block_tables, positions, seq_lens)
        return logits
