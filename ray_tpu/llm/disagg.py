"""Disaggregated prefill/decode: KV-page streaming between LLM replicas.

Prefill and decode have opposite resource profiles — prefill is one long
compute-bound burst, decode is thousands of tiny latency-bound steps — so
co-scheduling them on one replica makes every chatty session's inter-token
latency hostage to whatever long prompt shares the batch. This module splits
the tiers: PrefillServer replicas run chunked prefill ONLY (engine built
with prefill_only=True), then stream the populated KV pages plus portable
request state to a decode replica, whose engine adopts the pages into its
own block table (BlockManager.adopt_blocks + ModelRunner.scatter_pages) and
enters decode directly.

Wire format (reuses the chunked raw-frame machinery the ring collectives
run on, collective/cpu_group.py):

    [u64 body len][u8 kind=3][KVHandoffMsg: state JSON + kv meta + trace ctx]
    [u64][u8 kind=1][97B _AMETA][raw k-page bytes]   x ceil(bytes/1MiB)
    [u64][u8 kind=1][97B _AMETA][raw v-page bytes]   x ceil(bytes/1MiB)
    <- [u64][u8 kind=2][JSON ack]

The head frame is the typed wire.KVHandoffMsg (kind 3): the portable
request state as JSON bytes plus the request's trace context, so the decode
replica's adopt span parent-links to the sender's handoff span and one
stitched trace covers PrefillServer -> decode replica -> migration target.
Receivers also still accept a bare JSON head frame (kind 2, the pre-trace
wire) — unknown-field/missing-field semantics match the TaskSpec wire.
Control frames are never pickle: the handoff hot path moves
zero pickled bytes end to end (counter-tested like the ring collectives),
and a decode replica never evals attacker-shaped pickles off a socket. Page
payloads ride kind-1 array frames straight out of / into the page buffers
via recv_into — no intermediate copies, dtype-agnostic (bf16 pages travel
as raw bytes; the logical dtype rides in the JSON meta).

Failure atomicity: one connection carries exactly one request, and the
receiver adopts only after BOTH arrays arrived whole. A prefill replica
dying mid-handoff just drops the connection — the decode engine adopts
nothing, and the router re-runs prefill on another replica (the sender only
reports success after the receiver's ack).
"""

from __future__ import annotations

import json
import socket
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.collective.cpu_group import (
    _AMETA, _HDR, _K_ARRAY, _chunks, _frame_views, _read_ameta, _read_hdr,
    _sock_recv_into, _sock_send)
from ray_tpu.core import serialization as _ser
from ray_tpu.runtime import wire
from ray_tpu.util import tracing

# Handoff control frame: JSON body (kinds 0/1 belong to cpu_group's wire).
_K_JSON = 2
# Typed control frame: wire.KVHandoffMsg body — the JSON request state plus
# the trace context that stitches the request's spans across the handoff.
_K_MSG = 3
_CHUNK_BYTES = 1 << 20


class HandoffError(RuntimeError):
    """KV handoff failed before the receiver acked adoption."""


def _send_json(sock: socket.socket, obj: dict,
               deadline: Optional[float] = None) -> None:
    body = json.dumps(obj).encode()
    _sock_send(sock, memoryview(_HDR.pack(len(body), _K_JSON) + body),
               None, deadline)


def _send_msg(sock: socket.socket, msg,
              deadline: Optional[float] = None) -> None:
    body = msg.encode()
    _sock_send(sock, memoryview(_HDR.pack(len(body), _K_MSG) + body),
               None, deadline)


def _recv_frame(sock: socket.socket, deadline: Optional[float] = None):
    """Receive one logical handoff message: ("json", dict) or a whole raw
    array reassembled across its chunk frames ("array", flat uint8)."""
    length, kind = _read_hdr(sock, None, deadline)
    if kind == _K_MSG:
        # Typed head frame: request state + trace context (KVHandoffMsg).
        # Decoded into the same meta dict shape the JSON frame carries so
        # everything downstream is agnostic to which head frame arrived.
        body = bytearray(length)
        _sock_recv_into(sock, memoryview(body), None, deadline)
        msg = wire.KVHandoffMsg.decode(bytes(body))
        meta = json.loads(msg.state_json.decode())
        meta["kv_dtype"] = msg.kv_dtype
        meta["kv_shape"] = list(msg.kv_shape)
        if msg.trace_id:
            meta["_trace"] = (msg.trace_id, msg.parent_span_id or None)
        return "json", meta
    if kind == _K_JSON:
        body = bytearray(length)
        _sock_recv_into(sock, memoryview(body), None, deadline)
        return "json", json.loads(bytes(body).decode())
    if kind != _K_ARRAY:
        raise HandoffError(f"handoff protocol error: unknown frame kind {kind}")
    dtype, shape, offset, nelems = _read_ameta(sock, None, deadline)
    out = np.empty(shape, dtype)
    flat = out.reshape(-1)
    total, got = flat.size, 0
    while True:
        if nelems:
            _sock_recv_into(
                sock, memoryview(flat[offset:offset + nelems]).cast("B"),
                None, deadline)
            got += nelems
        _ser.counters["deserialize_fast"] += 1
        if got >= total:
            return "array", flat
        length, kind = _read_hdr(sock, None, deadline)
        if kind != _K_ARRAY:
            raise HandoffError("handoff protocol error: truncated array stream")
        _, _, offset, nelems = _read_ameta(sock, None, deadline)


def _send_array(sock: socket.socket, arr: np.ndarray,
                deadline: Optional[float] = None) -> None:
    """Stream one array as chunked raw frames; dtype-agnostic (bytes view)."""
    flat = np.ascontiguousarray(arr).reshape(-1).view(np.uint8)
    for off, n in _chunks(0, flat.size, _CHUNK_BYTES):
        for view in _frame_views(flat[off:off + n], flat.shape, off):
            _sock_send(sock, view, None, deadline)


def send_handoff(address, state: dict, k_pages, v_pages, *,
                 timeout: float = 60.0) -> dict:
    """Stream one prefilled request to a decode replica's KVStreamServer.

    Blocks until the receiver acks adoption — only then may the sender
    release its own pages and report success upstream (an unacked handoff
    is treated as never having happened; the router re-runs prefill)."""
    k = np.ascontiguousarray(k_pages)
    v = np.ascontiguousarray(v_pages)
    migrated = bool(state.get("migrated"))
    with tracing.span("llm:kv_handoff", "llm",
                      request_id=str(state.get("id", "")),
                      migrated=migrated, bytes=int(k.nbytes + v.nbytes)):
        # The trace ids captured INSIDE the span: the receiver's adopt span
        # parent-links to this handoff span, not to the caller's.
        msg = wire.KVHandoffMsg(
            state_json=json.dumps(state).encode(),
            kv_dtype=str(k.dtype), kv_shape=list(k.shape),
            migrated=migrated,
            trace_id=tracing.current_trace_id() or b"",
            parent_span_id=tracing.current_span_id() or b"")
        deadline = time.monotonic() + timeout
        with socket.create_connection(tuple(address), timeout=timeout) as sock:
            sock.settimeout(timeout)
            _send_msg(sock, msg, deadline)
            _send_array(sock, k, deadline)
            _send_array(sock, v, deadline)
            kind, ack = _recv_frame(sock, deadline)
    if kind != "json" or not ack.get("ok"):
        raise HandoffError(f"decode replica rejected handoff: {ack}")
    return ack


def migrate_session(address, state: dict, k_pages, v_pages, *,
                    timeout: float = 60.0) -> dict:
    """Replica->replica live session migration: the prefill->decode handoff
    wire generalized. `state` is an LLMEngine.export_session "kv" export —
    possibly mid-generation (output tokens + their KV pages ride along) —
    and the adopter resumes decode exactly where the exporter stopped, with
    zero re-prefill. Same whole-stream-or-discard atomicity and zero-pickle
    guarantees as send_handoff: an unacked migration never happened, and
    the caller falls back to seeded replay from the prompt."""
    meta = dict(state)
    meta["migrated"] = True
    return send_handoff(address, meta, k_pages, v_pages, timeout=timeout)


class KVStreamServer:
    """Decode-side handoff listener: adopts streamed KV pages atomically.

    One daemon thread accepts connections; each connection carries exactly
    one request. A connection that dies mid-stream is discarded whole —
    `adopt_fn(state, k_pages, v_pages) -> bool` runs only once both arrays
    arrived intact, so partial prefill state can never enter the decode
    engine's block table."""

    def __init__(self, adopt_fn: Callable[[dict, np.ndarray, np.ndarray], bool],
                 host: str = "127.0.0.1", port: int = 0,
                 timeout: float = 60.0):
        self._adopt = adopt_fn
        self._timeout = timeout
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.address: Tuple[str, int] = self._sock.getsockname()
        self._closed = False
        self.handoffs_adopted = 0
        self.handoffs_rejected = 0
        self._thread = threading.Thread(
            target=self._serve, name="kv-handoff-listener", daemon=True)
        self._thread.start()

    def _serve(self):
        while not self._closed:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # closed
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True, name="kv-handoff-conn").start()

    def _handle(self, conn: socket.socket):
        with conn:
            conn.settimeout(self._timeout)
            try:
                kind, meta = _recv_frame(conn)
                if kind != "json":
                    raise HandoffError("handoff must start with a JSON frame")
                _, kflat = _recv_frame(conn)
                _, vflat = _recv_frame(conn)
            except Exception:
                # Partial stream (sender died / malformed): adopt NOTHING.
                self.handoffs_rejected += 1
                return
            trace_id, parent = meta.pop("_trace", (None, None))
            try:
                dtype = np.dtype(meta.pop("kv_dtype"))
                shape = tuple(meta.pop("kv_shape"))
                k = kflat.view(dtype).reshape(shape)
                v = vflat.view(dtype).reshape(shape)
                # Adopt under the sender's trace context so this side of the
                # handoff — and any spans the adopt path opens — stitches
                # into the request's trace across the process boundary.
                with tracing.trace_context(trace_id, parent):
                    with tracing.span("llm:kv_adopt", "llm",
                                      request_id=str(meta.get("id", "")),
                                      migrated=bool(meta.get("migrated"))):
                        ok = bool(self._adopt(meta, k, v))
            except Exception as e:
                self.handoffs_rejected += 1
                try:
                    _send_json(conn, {"ok": False, "error": repr(e)})
                except Exception:
                    pass
                return
            if ok:
                self.handoffs_adopted += 1
                from ray_tpu.runtime import metric_defs

                metric_defs.LLM_KV_HANDOFFS.inc()
            else:
                self.handoffs_rejected += 1
            try:
                _send_json(conn, {"ok": ok, "id": meta.get("id")})
            except Exception:
                pass

    def close(self):
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass


class PrefillServer:
    """Replica callable for the prefill tier.

    Runs chunked prefill ONLY (the engine never takes a decode tick), then
    exports the request — state + populated KV pages — and streams it to
    the decode replica named by the caller. Requests that finish AT prefill
    (max_tokens == 1, stop token on the first sample) complete here and
    return their response inline: there is nothing left to decode."""

    def __init__(self, llm_config):
        from ray_tpu.llm.serving import build_engine

        self.engine = build_engine(llm_config, prefill_only=True)
        self.tokenizer = llm_config.tokenizer
        self._lock = threading.Lock()
        # EWMA prefill throughput (tokens/s): the router's TTFT estimator
        # divides queued prefill tokens by this.
        self._prefill_tps = 0.0

    def _parse(self, request: Dict):
        from ray_tpu.llm.serving import LLMServer

        return LLMServer._parse(self, request)

    def prefill(self, request: Dict, decode_address) -> Dict:
        """Prefill one request and hand it to `decode_address` (a decode
        replica's KVStreamServer). Returns {"handoff": True, "rid": ...} on
        success; {"handoff": False, "response": ...} when the request
        finished during prefill."""
        prompt, params, lora_name, rid = self._parse(request)
        t0 = time.monotonic()
        t0_wall = time.time()
        with self._lock:
            # A router-assigned request_id rides through so the decode-side
            # stream keeps the router's name for the request (failover
            # replays re-derive the same sampling seed from it).
            rid = self.engine.add_request(prompt, params, request_id=rid,
                                          lora_name=lora_name)
            final = None
            while True:
                outs = self.engine.step()
                mine = [o for o in outs if o.request_id == rid]
                if any(o.finished for o in mine):
                    final = next(o for o in mine if o.finished)
                    break
                if mine:
                    break  # first token emitted: prefill complete
                if not self.engine.has_unfinished():
                    raise RuntimeError(f"request {rid} vanished mid-prefill")
            if final is not None:
                # Finished AT prefill: the engine recorded the lifecycle
                # spans when the request finished; nothing to hand off.
                return {"handoff": False, "rid": rid,
                        "response": _completion_response(final)}
            state = self.engine.export_request(rid)
            blocks = state.pop("blocks")
            k, v = self.engine.runner.gather_pages(blocks)
            self.engine.block_manager.release_blocks(blocks)
            elapsed = max(time.monotonic() - t0, 1e-6)
            tps = len(prompt) / elapsed
            self._prefill_tps = (tps if self._prefill_tps == 0.0
                                 else 0.8 * self._prefill_tps + 0.2 * tps)
            t1_wall = time.time()
        # Stream outside the lock: the socket write must not serialize the
        # next request's prefill compute behind network time. The handoff
        # span (opened inside send_handoff) joins the request's trace — the
        # trace id is derived from the rid, so this stitches to the router's
        # root span without any context having crossed the RPC.
        with tracing.trace_context(tracing.request_trace_id(rid), None):
            tracing.record_span("llm:prefill", "llm", t0_wall, t1_wall,
                                request_id=rid, tokens=len(prompt),
                                tier="prefill")
            ack = send_handoff(decode_address, state, k, v)
        return {"handoff": True, "rid": rid, "ack": ack,
                "prefill_tokens_per_s": round(self._prefill_tps, 1)}

    def engine_stats(self) -> Dict:
        with self._lock:
            s = self.engine.stats()
        s["prefill_tokens_per_s"] = round(self._prefill_tps, 1)
        s["role"] = "prefill"
        return s


def _completion_response(out) -> Dict:
    """OpenAI-ish completion body from a finished RequestOutput (shared by
    LLMServer.completions and the prefill-finishes-everything path)."""
    return {
        "id": out.request_id,
        "object": "text_completion",
        "choices": [{
            "text": out.text,
            "token_ids": out.output_token_ids,
            "finish_reason": out.finish_reason,
        }],
        "usage": {
            "prompt_tokens": len(out.prompt_token_ids),
            "completion_tokens": len(out.output_token_ids),
        },
    }
