"""Token-throughput-aware LLM router: prefix affinity, pow2, SLO admission.

Replaces the blind client-side `DeploymentHandle._pick_replica` (power-of-
two on the caller's OWN in-flight count) for LLM apps with a router
deployment that sees what actually matters for token throughput:

  1. **Prefix-cache affinity.** The engine's automatic prefix cache keys
     blocks by a salted digest chain over prompt blocks
     (engine.prefix_digest_chain). The router keeps its own chain -> replica
     map under its OWN salt (replica salts are per-process and deliberately
     irreproducible): route a prompt to the replica that most recently
     served its longest matching chain and the replica-side cache hits on
     the shared prefix — prefill skips those blocks entirely.
  2. **Session / LoRA affinity.** A session's follow-up turn extends its
     previous context; its KV pages are still hot on the replica that
     served the last turn.
  3. **Power-of-two-choices on real load** — queue depth (waiting +
     prefilling + running from LLMServer.engine_stats()), router-side
     in-flight, and KV occupancy — instead of the handle's client-local
     in-flight count.
  4. **SLO-aware admission.** Projected TTFT = queued prefill tokens ahead
     of this request / measured prefill throughput. When it exceeds the
     deployment's slo_ttft_s, shed NOW with a 429-shaped error instead of
     letting every queue grow unboundedly (the shed is cheap for the client
     to retry elsewhere; a timed-out request holds KV pages the whole way).

In disaggregated mode (LLMConfig.disaggregate > 0) the router also drives
the prefill tier: pick a prefill replica, hand it the decode replica's KV
handoff address, and collect the completion from the decode replica once
the pages are adopted (llm/disagg.py). A prefill replica dying mid-handoff
is retried on the remaining prefill replicas — the handoff wire is atomic,
so a half-streamed request never enters any decode engine.

RouterCore is deliberately cluster-free (pure routing state + arithmetic)
so tests and the microbench drive it against in-process engines; LLMRouter
is the serve deployment wrapping it around real replica handles.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ray_tpu.llm.engine import prefix_digest_chain

# Load-score weight of KV occupancy relative to one queued request: a
# replica with a full cache is one whose next admission will evict reusable
# prefixes, so treat it as ~2 requests heavier.
_KV_PRESSURE_WEIGHT = 2.0
# An affinity owner more than this many score points heavier than the
# lightest replica loses the request anyway (cache reuse never justifies
# piling onto a hotspot).
_AFFINITY_IMBALANCE = 8.0


class LocalReplica:
    """In-process replica adapter (tests / microbench): same call surface
    as ActorReplica, with RpcChaos fault injection honored so chaos tests
    exercise the router's retry path without a cluster."""

    def __init__(self, obj: Any, name: str = ""):
        self._obj = obj
        self.name = name or type(obj).__name__

    def call(self, method: str, *args, **kwargs):
        from ray_tpu.runtime.chaos import chaos

        c = chaos()
        if c.enabled:
            import asyncio

            asyncio.run(c.intercept_client(f"{self.name}.{method}"))
        return getattr(self._obj, method)(*args, **kwargs)


class ActorReplica:
    """Replica actor handle adapter (the real serve path)."""

    def __init__(self, handle: Any, name: str = "", timeout: float = 600.0):
        self._handle = handle
        self._timeout = timeout
        self.name = name

    def call(self, method: str, *args, **kwargs):
        import ray_tpu

        ref = self._handle.handle_request.remote(method, list(args), kwargs)
        return ray_tpu.get(ref, timeout=self._timeout)


class RouterCore:
    """Routing state machine: affinity maps, load scores, admission gate.

    Indexes replicas 0..n-1; the owner (LLMRouter or a test) maps indexes
    to actual replica objects and feeds `pick`/`admit` fresh engine_stats
    payloads. Thread-safe under one internal lock (decisions are cheap;
    the expensive work — stats RPCs, token streaming — happens outside)."""

    def __init__(self, n_replicas: int, *, block_size: int = 16,
                 slo_ttft_s: float = 0.0, prefix_lru: int = 8192,
                 prefill_tps: Optional[float] = None):
        if n_replicas < 1:
            raise ValueError("router needs at least one replica")
        self.n = n_replicas
        self.block_size = block_size
        self.slo_ttft_s = float(slo_ttft_s)
        self._lock = threading.Lock()
        # Router-local salt: chains here never meet replica-side chains
        # (those are salted per process); only internal consistency matters.
        self._salt = os.urandom(16)
        # digest -> replica idx, LRU-bounded; last writer wins (that replica
        # holds the freshest copy of the blocks).
        self._prefix_owner: "collections.OrderedDict[bytes, int]" = \
            collections.OrderedDict()
        self._prefix_lru = prefix_lru
        self._session_owner: Dict[str, int] = {}
        self._inflight = [0] * n_replicas
        # Prefill-throughput EWMA feeding the TTFT estimator; a pinned
        # value (tests) disables the online update.
        self._prefill_tps = prefill_tps or 0.0
        self._prefill_tps_pinned = prefill_tps is not None
        self.affinity_hits = 0
        self.affinity_misses = 0
        self.shed_count = 0

    # ---- affinity --------------------------------------------------------

    def digest_chain(self, prompt: Sequence[int],
                     lora_name: Optional[str] = None) -> List[bytes]:
        return prefix_digest_chain(prompt, self.block_size, salt=self._salt,
                                   seed=(lora_name or "").encode())

    def _remember(self, chain: List[bytes], idx: int):
        for d in chain:
            if d in self._prefix_owner:
                self._prefix_owner.move_to_end(d)
            self._prefix_owner[d] = idx
        while len(self._prefix_owner) > self._prefix_lru:
            self._prefix_owner.popitem(last=False)

    # ---- load ------------------------------------------------------------

    def _load_score(self, idx: int, stats: Sequence[Optional[Dict]]) -> float:
        s = stats[idx] if idx < len(stats) else None
        score = float(self._inflight[idx])
        if s:
            score += s.get("waiting", 0) + s.get("prefilling", 0) \
                + s.get("running", 0)
            total = s.get("total_kv_blocks", 0)
            if total:
                score += _KV_PRESSURE_WEIGHT * (
                    1.0 - s.get("free_kv_blocks", 0) / total)
        return score

    # ---- decisions -------------------------------------------------------

    def pick(self, prompt: Sequence[int], *,
             session_id: Optional[str] = None,
             lora_name: Optional[str] = None,
             stats: Optional[Sequence[Optional[Dict]]] = None
             ) -> Tuple[int, Dict]:
        """Choose a replica. Returns (idx, decision) where decision carries
        the reason ("session" | "prefix" | "pow2") and matched_blocks."""
        import random

        stats = stats if stats is not None else [None] * self.n
        chain = self.digest_chain(prompt, lora_name)
        with self._lock:
            scores = [self._load_score(i, stats) for i in range(self.n)]
            floor = min(scores)
            idx: Optional[int] = None
            decision = {"reason": "pow2", "matched_blocks": 0}
            if session_id is not None:
                owner = self._session_owner.get(session_id)
                if owner is not None and owner < self.n \
                        and scores[owner] - floor <= _AFFINITY_IMBALANCE:
                    idx = owner
                    decision = {"reason": "session", "matched_blocks": 0}
            if idx is None:
                # Deepest cached chain wins: scan from the longest prefix
                # down so a replica holding 8 blocks beats one holding 2.
                for i in range(len(chain) - 1, -1, -1):
                    owner = self._prefix_owner.get(chain[i])
                    if owner is None or owner >= self.n:
                        continue
                    if scores[owner] - floor > _AFFINITY_IMBALANCE:
                        break  # owner is a hotspot; fall through to pow2
                    idx = owner
                    decision = {"reason": "prefix", "matched_blocks": i + 1}
                    break
            if idx is None:
                if self.n == 1:
                    idx = 0
                else:
                    a, b = random.sample(range(self.n), 2)
                    idx = a if scores[a] <= scores[b] else b
                self.affinity_misses += 1
            else:
                self.affinity_hits += 1
            if session_id is not None:
                self._session_owner[session_id] = idx
            self._remember(chain, idx)
            return idx, decision

    def admit(self, idx: int, prompt_len: int,
              stats: Optional[Sequence[Optional[Dict]]] = None
              ) -> Tuple[bool, float]:
        """SLO admission gate: (ok, projected_ttft_s). Projected TTFT is
        the prefill tokens queued ahead of this request divided by measured
        prefill throughput; with no SLO configured everything admits."""
        if self.slo_ttft_s <= 0.0:
            return True, 0.0
        with self._lock:
            tps = self._prefill_tps
        if tps <= 0.0:
            return True, 0.0  # no throughput signal yet: never shed blind
        s = (stats[idx] if stats is not None and idx < len(stats) else None) \
            or {}
        queued = s.get("queued_prefill_tokens", 0)
        projected = (queued + prompt_len) / tps
        if projected > self.slo_ttft_s:
            with self._lock:
                self.shed_count += 1
            return False, projected
        return True, projected

    def observe_prefill(self, tokens: int, seconds: float):
        """Feed the TTFT estimator with a measured prefill."""
        if self._prefill_tps_pinned or seconds <= 0 or tokens <= 0:
            return
        rate = tokens / seconds
        with self._lock:
            self._prefill_tps = (rate if self._prefill_tps == 0.0
                                 else 0.8 * self._prefill_tps + 0.2 * rate)

    def start(self, idx: int):
        with self._lock:
            self._inflight[idx] += 1

    def finish(self, idx: int):
        with self._lock:
            self._inflight[idx] = max(0, self._inflight[idx] - 1)


def prefill_with_retry(prefill_replicas: Sequence[Any], request: Dict,
                       decode_address) -> Dict:
    """Run prefill on the first replica that survives it.

    The handoff wire is atomic (llm/disagg.py): a replica that dies
    mid-stream leaves NOTHING adopted on the decode side, so re-running
    the whole prefill elsewhere is always correct — just wasted compute."""
    last: Optional[Exception] = None
    for replica in prefill_replicas:
        try:
            return replica.call("prefill", request, decode_address)
        except Exception as e:  # ConnectionLost, HandoffError, socket death
            last = e
    raise RuntimeError(
        f"prefill failed on all {len(prefill_replicas)} replicas") from last


class LLMRouter:
    """The serve deployment fronting the LLM fleet (build_routed_app).

    Requests: same body as LLMServer.completions plus optional
    "session_id". Responses: the completion dict, or a 429-shaped
    {"error": {"code": 429, ...}} when SLO admission sheds."""

    STATS_TTL_S = 0.25

    def __init__(self, llm_config, engine_deployment: str,
                 prefill_deployment: Optional[str] = None):
        self.config = llm_config
        self.deployment = engine_deployment
        self._prefill_deployment = prefill_deployment
        # Replica handles resolve on the FIRST REQUEST, not here: this
        # __init__ runs while the controller is still blocked deploying the
        # router itself, so calling back into it (get_replicas) from here
        # deadlocks until the get times out and kills the deploy.
        self.replicas: List[Any] = []
        self.prefill_replicas: List[Any] = []
        self.core: Optional[RouterCore] = None
        self._resolve_lock = threading.Lock()
        self._stats: List[Optional[Dict]] = []
        self._stats_t = 0.0
        self._stats_lock = threading.Lock()
        # decode idx -> KV handoff address, resolved once per replica.
        self._handoff_addrs: Dict[int, Any] = {}

    # ---- replica state ---------------------------------------------------

    def _ensure_replicas(self) -> None:
        if self.core is not None:
            return
        with self._resolve_lock:
            if self.core is not None:
                return
            from ray_tpu import serve

            handle = serve.get_deployment_handle(self.deployment)
            self.replicas = [
                ActorReplica(h, name=f"{self.deployment}#{i}")
                for i, h in enumerate(handle.replica_handles())]
            if self._prefill_deployment:
                ph = serve.get_deployment_handle(self._prefill_deployment)
                self.prefill_replicas = [
                    ActorReplica(h, name=f"{self._prefill_deployment}#{i}")
                    for i, h in enumerate(ph.replica_handles())]
            self._stats = [None] * len(self.replicas)
            # core is the publication barrier: assigned LAST, so a racing
            # reader that sees it non-None sees resolved replicas too.
            self.core = RouterCore(
                len(self.replicas), block_size=self.config.block_size,
                slo_ttft_s=self.config.slo_ttft_s)

    def _fresh_stats(self) -> List[Optional[Dict]]:
        now = time.monotonic()
        with self._stats_lock:
            if now - self._stats_t < self.STATS_TTL_S:
                return self._stats
            self._stats_t = now
        stats: List[Optional[Dict]] = []
        for r in self.replicas:
            try:
                stats.append(r.call("engine_stats"))
            except Exception:
                stats.append(None)  # unreachable replica scores as unknown
        with self._stats_lock:
            self._stats = stats
        return stats

    def _handoff_addr(self, idx: int):
        addr = self._handoff_addrs.get(idx)
        if addr is None:
            addr = self.replicas[idx].call("handoff_address")
            self._handoff_addrs[idx] = addr
        return addr

    # ---- API -------------------------------------------------------------

    def __call__(self, request: Dict) -> Dict:
        return self.completions(request)

    def completions(self, request: Dict) -> Dict:
        from ray_tpu.runtime import events, metric_defs

        self._ensure_replicas()
        prompt = request.get("prompt", [])
        token_prompt = (list(prompt.encode()) if isinstance(prompt, str)
                        else list(prompt))
        stats = self._fresh_stats()
        idx, decision = self.core.pick(
            token_prompt, session_id=request.get("session_id"),
            lora_name=request.get("lora_name"), stats=stats)
        metric_defs.LLM_ROUTER_AFFINITY.inc(tags={
            "outcome": "hit" if decision["reason"] != "pow2" else "miss"})
        ok, projected = self.core.admit(idx, len(token_prompt), stats)
        if not ok:
            metric_defs.LLM_ROUTER_SHED.inc(
                tags={"deployment": self.deployment})
            events.emit(events.LLM_REQUEST_SHED,
                        f"shed: projected TTFT {projected:.2f}s > SLO "
                        f"{self.core.slo_ttft_s:.2f}s",
                        severity=events.WARNING, source="llm-router",
                        labels={"projected_ttft_s": f"{projected:.3f}",
                                "slo_ttft_s": f"{self.core.slo_ttft_s:.3f}",
                                "replica": str(idx)})
            return {"error": {"code": 429, "type": "overloaded",
                              "message": "projected TTFT "
                                         f"{projected:.2f}s exceeds SLO; "
                                         "retry with backoff"}}
        self.core.start(idx)
        try:
            if self.prefill_replicas:
                return self._disagg_completions(request, idx)
            t0 = time.monotonic()
            resp = self.replicas[idx].call("completions", request)
            self.core.observe_prefill(
                len(token_prompt), max(time.monotonic() - t0, 1e-6))
            return resp
        finally:
            self.core.finish(idx)

    def _disagg_completions(self, request: Dict, decode_idx: int) -> Dict:
        t0 = time.monotonic()
        result = prefill_with_retry(self.prefill_replicas, request,
                                    self._handoff_addr(decode_idx))
        if not result.get("handoff"):
            return result["response"]  # finished at prefill
        prompt = request.get("prompt", [])
        n = len(prompt.encode() if isinstance(prompt, str) else prompt)
        self.core.observe_prefill(n, max(time.monotonic() - t0, 1e-6))
        return self.replicas[decode_idx].call(
            "completions_collect", result["rid"])

    def router_stats(self) -> Dict:
        self._ensure_replicas()
        return {
            "replicas": len(self.replicas),
            "prefill_replicas": len(self.prefill_replicas),
            "affinity_hits": self.core.affinity_hits,
            "affinity_misses": self.core.affinity_misses,
            "shed_count": self.core.shed_count,
        }
