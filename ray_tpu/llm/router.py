"""Token-throughput-aware LLM router: prefix affinity, pow2, SLO admission,
replica health, failover, and drain-plane session migration.

Replaces the blind client-side `DeploymentHandle._pick_replica` (power-of-
two on the caller's OWN in-flight count) for LLM apps with a router
deployment that sees what actually matters for token throughput:

  1. **Prefix-cache affinity.** The engine's automatic prefix cache keys
     blocks by a salted digest chain over prompt blocks
     (engine.prefix_digest_chain). The router keeps its own chain -> replica
     map under its OWN salt (replica salts are per-process and deliberately
     irreproducible): route a prompt to the replica that most recently
     served its longest matching chain and the replica-side cache hits on
     the shared prefix — prefill skips those blocks entirely.
  2. **Session / LoRA affinity.** A session's follow-up turn extends its
     previous context; its KV pages are still hot on the replica that
     served the last turn.
  3. **Power-of-two-choices on real load** — queue depth (waiting +
     prefilling + running from LLMServer.engine_stats()), router-side
     in-flight, and KV occupancy — instead of the handle's client-local
     in-flight count.
  4. **SLO-aware admission.** Projected TTFT = queued prefill tokens ahead
     of this request / measured prefill throughput. When it exceeds the
     deployment's slo_ttft_s, shed NOW with a 429-shaped error instead of
     letting every queue grow unboundedly (the shed is cheap for the client
     to retry elsewhere; a timed-out request holds KV pages the whole way).

Fleet resilience (FleetSupervisor):

  5. **Health + failover.** Per-replica liveness is tracked from
     engine_stats() probe failures and request-call failures; a dead
     replica is ejected (its prefix-digest owner-LRU and session-affinity
     entries pruned eagerly, so no request routes to a corpse until LRU
     eviction) and every in-flight request it held is replayed on a
     survivor under the SAME request_id — the engine seeds its sampler
     from crc32(request_id), so the retried generation is token-identical
     and the client sees one completed response plus an
     LLM_REQUEST_FAILOVER event, never a stack trace.
  6. **Live session migration.** When a replica's node enters
     NODE_DRAINING (or the replica policy retires it for scale-down), the
     replica stops admitting and exports its in-flight requests — KV pages
     included — to an adoptive replica over the zero-pickle raw-frame wire
     (llm/disagg.py migrate_session); the router atomically remaps
     session/prefix affinity to the target, so drained sessions resume
     mid-generation with zero re-prefill.
  7. **Telemetry-driven scaling.** A ReplicaPolicy (llm/replica_policy.py)
     turns router-visible queue-delay / KV-pressure signals into a desired
     replica count; scale-down is drain-then-migrate, never kill.

In disaggregated mode (LLMConfig.disaggregate > 0) the router also drives
the prefill tier: pick a prefill replica, hand it the decode replica's KV
handoff address, and collect the completion from the decode replica once
the pages are adopted (llm/disagg.py). A prefill replica dying mid-handoff
is retried on the remaining prefill replicas — the handoff wire is atomic,
so a half-streamed request never enters any decode engine — and a whole-
tier prefill outage surfaces as a 503 without touching decode-replica
health (prefill failures are never blamed on the decode fleet). A decode
replica dying mid-collect rides the same failover path as colocated mode
(the orphaned request is aborted server-side when the replica is still
reachable, so no KV leaks).

RouterCore and FleetSupervisor are deliberately cluster-free (pure routing
state + adapters with a .call surface) so tests and the microbench drive
them against in-process engines; LLMRouter is the serve deployment
wrapping them around real replica handles.
"""

from __future__ import annotations

import collections
import os
import re
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Sequence, Set, Tuple

from ray_tpu.llm.engine import prefix_digest_chain

# Load-score weight of KV occupancy relative to one queued request: a
# replica with a full cache is one whose next admission will evict reusable
# prefixes, so treat it as ~2 requests heavier.
_KV_PRESSURE_WEIGHT = 2.0
# An affinity owner more than this many score points heavier than the
# lightest replica loses the request anyway (cache reuse never justifies
# piling onto a hotspot).
_AFFINITY_IMBALANCE = 8.0


class NoHealthyReplicasError(RuntimeError):
    """Every replica is ejected or draining; nothing can take the request."""


class LocalReplica:
    """In-process replica adapter (tests / microbench): same call surface
    as ActorReplica, with RpcChaos fault injection honored so chaos tests
    exercise the router's retry path without a cluster."""

    def __init__(self, obj: Any, name: str = ""):
        self._obj = obj
        self.name = name or type(obj).__name__
        self.key = f"local:{self.name}:{id(obj):x}"

    def call(self, method: str, *args, **kwargs):
        from ray_tpu.runtime.chaos import chaos

        kwargs.pop("_timeout", None)
        c = chaos()
        if c.enabled:
            import asyncio

            asyncio.run(c.intercept_client(f"{self.name}.{method}"))
        return getattr(self._obj, method)(*args, **kwargs)


class ActorReplica:
    """Replica actor handle adapter (the real serve path)."""

    def __init__(self, handle: Any, name: str = "", timeout: float = 600.0):
        self._handle = handle
        self._timeout = timeout
        self.name = name
        aid = getattr(handle, "_actor_id", None)
        self.key = (bytes(aid).hex() if isinstance(aid, (bytes, bytearray))
                    else str(aid))

    def call(self, method: str, *args, **kwargs):
        import ray_tpu

        # `_timeout` is adapter-reserved (health probes use short deadlines
        # so a dead actor can't wedge the router for the full RPC timeout).
        timeout = kwargs.pop("_timeout", self._timeout)
        ref = self._handle.handle_request.remote(method, list(args), kwargs)
        return ray_tpu.get(ref, timeout=timeout)


class RouterCore:
    """Routing state machine: affinity maps, load scores, admission gate,
    and per-replica health/draining flags.

    Indexes replicas 0..n-1; the owner (FleetSupervisor or a test) maps
    indexes to actual replica objects and feeds `pick`/`admit` fresh
    engine_stats payloads. Slots are append-only: an ejected replica keeps
    its index (never routable again) and replacements get fresh indexes
    via add_replica, so affinity maps never alias across replica
    generations. Thread-safe under one internal lock (decisions are cheap;
    the expensive work — stats RPCs, token streaming — happens outside)."""

    def __init__(self, n_replicas: int, *, block_size: int = 16,
                 slo_ttft_s: float = 0.0, prefix_lru: int = 8192,
                 prefill_tps: Optional[float] = None,
                 fail_threshold: int = 3):
        if n_replicas < 1:
            raise ValueError("router needs at least one replica")
        self.n = n_replicas
        self.block_size = block_size
        self.slo_ttft_s = float(slo_ttft_s)
        self._lock = threading.Lock()
        # Router-local salt: chains here never meet replica-side chains
        # (those are salted per process); only internal consistency matters.
        self._salt = os.urandom(16)
        # digest -> replica idx, LRU-bounded; last writer wins (that replica
        # holds the freshest copy of the blocks).
        self._prefix_owner: "collections.OrderedDict[bytes, int]" = \
            collections.OrderedDict()
        self._prefix_lru = prefix_lru
        self._session_owner: Dict[str, int] = {}
        self._inflight = [0] * n_replicas
        # Health: consecutive stats-probe failures accumulate toward
        # fail_threshold; a hard call failure ejects immediately. Draining
        # replicas stay healthy but take no new picks (their sessions are
        # mid-migration).
        self._healthy = [True] * n_replicas
        self._draining = [False] * n_replicas
        self._fail_counts = [0] * n_replicas
        self.fail_threshold = max(1, int(fail_threshold))
        self.ejected_count = 0
        # Prefill-throughput EWMA feeding the TTFT estimator; a pinned
        # value (tests) disables the online update.
        self._prefill_tps = prefill_tps or 0.0
        self._prefill_tps_pinned = prefill_tps is not None
        self.affinity_hits = 0
        self.affinity_misses = 0
        self.shed_count = 0

    # ---- affinity --------------------------------------------------------

    def digest_chain(self, prompt: Sequence[int],
                     lora_name: Optional[str] = None) -> List[bytes]:
        return prefix_digest_chain(prompt, self.block_size, salt=self._salt,
                                   seed=(lora_name or "").encode())

    def _remember(self, chain: List[bytes], idx: int):
        for d in chain:
            if d in self._prefix_owner:
                self._prefix_owner.move_to_end(d)
            self._prefix_owner[d] = idx
        while len(self._prefix_owner) > self._prefix_lru:
            self._prefix_owner.popitem(last=False)

    # ---- health ----------------------------------------------------------

    def is_healthy(self, idx: int) -> bool:
        return 0 <= idx < self.n and self._healthy[idx]

    def is_routable(self, idx: int) -> bool:
        return (0 <= idx < self.n and self._healthy[idx]
                and not self._draining[idx])

    def healthy_count(self) -> int:
        return sum(self._healthy)

    def routable_count(self) -> int:
        return sum(h and not d for h, d in zip(self._healthy, self._draining))

    def note_success(self, idx: int) -> None:
        with self._lock:
            if 0 <= idx < self.n:
                self._fail_counts[idx] = 0

    def note_failure(self, idx: int, hard: bool = False) -> bool:
        """Record a probe/call failure; True when the replica should now be
        ejected (hard failure, or fail_threshold consecutive probes)."""
        with self._lock:
            if not (0 <= idx < self.n) or not self._healthy[idx]:
                return False
            self._fail_counts[idx] += 1
            return hard or self._fail_counts[idx] >= self.fail_threshold

    def set_draining(self, idx: int, flag: bool = True) -> None:
        with self._lock:
            if 0 <= idx < self.n:
                self._draining[idx] = flag

    def eject(self, idx: int) -> Optional[Dict]:
        """Declare a replica dead: no pick ever returns it again, and its
        prefix-digest owner-LRU and session-stickiness entries are pruned
        EAGERLY (the affinity-leak fix — without this, requests keep
        routing to the corpse until LRU eviction). Returns prune counts,
        or None when already ejected (idempotent)."""
        with self._lock:
            if not (0 <= idx < self.n) or not self._healthy[idx]:
                return None
            self._healthy[idx] = False
            self._draining[idx] = False
            dead_digests = [d for d, o in self._prefix_owner.items()
                            if o == idx]
            for d in dead_digests:
                del self._prefix_owner[d]
            dead_sessions = [s for s, o in self._session_owner.items()
                             if o == idx]
            for s in dead_sessions:
                del self._session_owner[s]
            self._inflight[idx] = 0
            self.ejected_count += 1
            return {"prefix_pruned": len(dead_digests),
                    "sessions_pruned": len(dead_sessions)}

    def remap(self, old: int, new: int) -> Dict:
        """Atomically repoint every affinity entry old -> new (the adoptive
        replica now holds the migrated sessions' KV), so follow-up turns
        land where the pages moved instead of re-prefilling elsewhere."""
        with self._lock:
            n_prefix = n_sessions = 0
            for d, o in self._prefix_owner.items():
                if o == old:
                    self._prefix_owner[d] = new
                    n_prefix += 1
            for s, o in self._session_owner.items():
                if o == old:
                    self._session_owner[s] = new
                    n_sessions += 1
            return {"prefix_remapped": n_prefix,
                    "sessions_remapped": n_sessions}

    def add_replica(self) -> int:
        """Grow the fleet by one slot (scale-up / replacement capacity)."""
        with self._lock:
            self.n += 1
            self._inflight.append(0)
            self._healthy.append(True)
            self._draining.append(False)
            self._fail_counts.append(0)
            return self.n - 1

    # ---- load ------------------------------------------------------------

    def _load_score(self, idx: int, stats: Sequence[Optional[Dict]]) -> float:
        s = stats[idx] if idx < len(stats) else None
        score = float(self._inflight[idx])
        if s:
            score += s.get("waiting", 0) + s.get("prefilling", 0) \
                + s.get("running", 0)
            total = s.get("total_kv_blocks", 0)
            if total:
                score += _KV_PRESSURE_WEIGHT * (
                    1.0 - s.get("free_kv_blocks", 0) / total)
        return score

    def load_score(self, idx: int,
                   stats: Sequence[Optional[Dict]]) -> float:
        with self._lock:
            return self._load_score(idx, stats)

    # ---- decisions -------------------------------------------------------

    def pick(self, prompt: Sequence[int], *,
             session_id: Optional[str] = None,
             lora_name: Optional[str] = None,
             stats: Optional[Sequence[Optional[Dict]]] = None,
             exclude: Optional[Set[int]] = None
             ) -> Tuple[int, Dict]:
        """Choose a replica. Returns (idx, decision) where decision carries
        the reason ("session" | "prefix" | "pow2") and matched_blocks.
        Only healthy, non-draining replicas (minus `exclude` — replicas a
        failover already tried) are candidates; raises
        NoHealthyReplicasError when none remain."""
        import random

        stats = stats if stats is not None else [None] * self.n
        chain = self.digest_chain(prompt, lora_name)
        exclude = exclude or set()
        with self._lock:
            elig = [i for i in range(self.n)
                    if self._healthy[i] and not self._draining[i]
                    and i not in exclude]
            if not elig:
                raise NoHealthyReplicasError(
                    f"no healthy replicas ({self.n} slots: "
                    f"{self.healthy_count()} healthy, "
                    f"{len(exclude)} excluded by this request)")
            elig_set = set(elig)
            scores = {i: self._load_score(i, stats) for i in elig}
            floor = min(scores.values())
            idx: Optional[int] = None
            decision = {"reason": "pow2", "matched_blocks": 0}
            if session_id is not None:
                owner = self._session_owner.get(session_id)
                if owner is not None and owner in elig_set \
                        and scores[owner] - floor <= _AFFINITY_IMBALANCE:
                    idx = owner
                    decision = {"reason": "session", "matched_blocks": 0}
            if idx is None:
                # Deepest cached chain wins: scan from the longest prefix
                # down so a replica holding 8 blocks beats one holding 2.
                for i in range(len(chain) - 1, -1, -1):
                    owner = self._prefix_owner.get(chain[i])
                    if owner is None or owner not in elig_set:
                        continue
                    if scores[owner] - floor > _AFFINITY_IMBALANCE:
                        break  # owner is a hotspot; fall through to pow2
                    idx = owner
                    decision = {"reason": "prefix", "matched_blocks": i + 1}
                    break
            if idx is None:
                if len(elig) == 1:
                    idx = elig[0]
                else:
                    a, b = random.sample(elig, 2)
                    idx = a if scores[a] <= scores[b] else b
                self.affinity_misses += 1
            else:
                self.affinity_hits += 1
            if session_id is not None:
                self._session_owner[session_id] = idx
            self._remember(chain, idx)
            return idx, decision

    def admit(self, idx: int, prompt_len: int,
              stats: Optional[Sequence[Optional[Dict]]] = None
              ) -> Tuple[bool, float]:
        """SLO admission gate: (ok, projected_ttft_s). Projected TTFT is
        the prefill tokens queued ahead of this request divided by measured
        prefill throughput; with no SLO configured everything admits."""
        if self.slo_ttft_s <= 0.0:
            return True, 0.0
        with self._lock:
            tps = self._prefill_tps
        if tps <= 0.0:
            return True, 0.0  # no throughput signal yet: never shed blind
        s = (stats[idx] if stats is not None and idx < len(stats) else None) \
            or {}
        queued = s.get("queued_prefill_tokens", 0)
        projected = (queued + prompt_len) / tps
        if projected > self.slo_ttft_s:
            with self._lock:
                self.shed_count += 1
            return False, projected
        return True, projected

    def observe_prefill(self, tokens: int, seconds: float):
        """Feed the TTFT estimator with a measured prefill."""
        if self._prefill_tps_pinned or seconds <= 0 or tokens <= 0:
            return
        rate = tokens / seconds
        with self._lock:
            self._prefill_tps = (rate if self._prefill_tps == 0.0
                                 else 0.8 * self._prefill_tps + 0.2 * rate)

    def start(self, idx: int):
        with self._lock:
            if idx < len(self._inflight):
                self._inflight[idx] += 1

    def finish(self, idx: int):
        with self._lock:
            if idx < len(self._inflight):
                self._inflight[idx] = max(0, self._inflight[idx] - 1)


class PrefillTierError(RuntimeError):
    """Every prefill replica failed transport-side: the tier is down.
    Routers report this upstream as a 503 — it is never attributed to the
    decode replica the request happened to be paired with."""


# Replica-side failures of the prefill→decode handoff wire (socket death,
# rejected adoption) cross the actor-RPC boundary re-wrapped in TaskError,
# so retryability is classified by message marker like the drain errors.
_HANDOFF_RETRY_RE = re.compile(
    r"HandoffError|ConnectionLost|Connection(Error|RefusedError|ResetError|"
    r"AbortedError)|BrokenPipeError|socket\.timeout")


def prefill_with_retry(prefill_replicas: Sequence[Any], request: Dict,
                       decode_address) -> Dict:
    """Run prefill on the first replica that survives it.

    The handoff wire is atomic (llm/disagg.py): a replica that dies
    mid-stream leaves NOTHING adopted on the decode side, so re-running
    the whole prefill elsewhere is always correct — just wasted compute.
    Only transport/handoff failures retry: an error the replica raised
    executing the request (validation ValueError from _parse) is
    deterministic — every replica would fail identically — so it
    propagates to the client immediately."""
    last: Optional[Exception] = None
    for replica in prefill_replicas:
        try:
            return replica.call("prefill", request, decode_address)
        except Exception as e:  # ConnectionLost, HandoffError, socket death
            if not (_is_transport_error(e)
                    or _HANDOFF_RETRY_RE.search(repr(e))):
                raise
            last = e
    raise PrefillTierError(
        f"prefill failed on all {len(prefill_replicas)} replicas") from last


# Replica exceptions cross actor RPC boundaries re-wrapped in transport
# error types, so classification is by message marker, not isinstance
# (serving.SessionMigratedError / ReplicaDrainingError embed these).
_MIGRATED_RE = re.compile(r"SESSION_MIGRATED (kv|replay)")


def _migrated_mode(exc: BaseException) -> Optional[str]:
    m = _MIGRATED_RE.search(repr(exc))
    return m.group(1) if m else None


def _is_draining_error(exc: BaseException) -> bool:
    return "REPLICA_DRAINING" in repr(exc)


def _is_transport_error(exc: BaseException) -> bool:
    """True when the error means the CALL never completed (actor death,
    wedged RPC, dropped connection) — the only failures that justify
    ejecting the replica and replaying the request elsewhere.

    A TaskError deliberately does NOT match: it means the replica executed
    the request and raised — validation errors (ValueError from _parse),
    per-request stream timeouts (RequestTimeoutError), engine bugs. The
    replica is alive and a replay would deterministically fail again, so
    those propagate to the client without touching replica health."""
    from ray_tpu.core import exceptions as exc_mod
    from ray_tpu.llm.serving import RequestTimeoutError
    from ray_tpu.runtime.rpc import RpcError

    # RequestTimeoutError is a TimeoutError — an OSError since py3.10 —
    # but it's the replica REPORTING a per-request deadline, not a dead
    # transport: exclude it before the OSError check.
    if isinstance(exc, (exc_mod.TaskError, RequestTimeoutError)):
        return False
    return isinstance(exc, (exc_mod.ActorError, exc_mod.GetTimeoutError,
                            exc_mod.WorkerCrashedError,
                            exc_mod.NodeDiedError, exc_mod.ObjectLostError,
                            RpcError, OSError))


class FleetSupervisor:
    """The fleet resilience engine around RouterCore: request failover,
    drain-plane session migration, node-event handling, and replica-count
    policy. Cluster-free — replicas are anything with the
    LocalReplica/ActorReplica `.call` surface, and scaling actions are
    injected callbacks — so the chaos tests drive the REAL failover and
    migration machinery against in-process engines."""

    STATS_TTL_S = 0.25
    STATS_TIMEOUT_S = 5.0

    def __init__(self, core: RouterCore, replicas: Sequence[Any], *,
                 deployment: str = "llm",
                 prefill_replicas: Sequence[Any] = (),
                 policy: Any = None,
                 scale_up_fn: Optional[Callable[[int], Any]] = None,
                 retire_fn: Optional[Callable[[int], Any]] = None,
                 prefix_store: Any = None):
        self.core = core
        self.replicas: List[Any] = list(replicas)
        self.prefill_replicas: List[Any] = list(prefill_replicas)
        self.deployment = deployment
        self.policy = policy
        # Cluster prefix table client (llm/prefix_store.py), optional: the
        # owner-LRU's fallback on owner ejection/restart, and the same-tick
        # hygiene hook when a replica is ejected.
        self.prefix_store = prefix_store
        self._scale_up_fn = scale_up_fn
        self._retire_fn = retire_fn
        self._lock = threading.Lock()        # replica list + drain state
        self._stats_lock = threading.Lock()
        self._stats: List[Optional[Dict]] = [None] * len(self.replicas)
        self._stats_t = 0.0
        # replica idx -> KV stream address, resolved once per replica.
        self._handoff_addrs: Dict[int, Any] = {}
        # Draining idx -> adoptive idx: where a consumer hit by
        # SessionMigratedError("kv") re-collects its stream.
        self._drain_target: Dict[int, int] = {}
        # Event-ring watermark (time.time() base, matching make_event):
        # starts at NOW so a (re)started supervisor never replays historical
        # NODE_DRAINING/NODE_DEAD events against replicas that live on a
        # node that drained and recovered before this supervisor existed.
        self._events_since = time.time()
        self.failovers = 0
        self.migrated_sessions = 0
        from ray_tpu.runtime import metric_defs as md

        tags = {"deployment": deployment}
        self._m_failovers = md.LLM_FAILOVERS.bind(tags)
        self._m_migrated = md.LLM_SESSIONS_MIGRATED.bind(tags)
        self._m_healthy = md.LLM_REPLICAS_HEALTHY.bind(tags)
        self._m_healthy.set(core.healthy_count())

    # ---- replica set -----------------------------------------------------

    def add_replica(self, replica: Any) -> int:
        """Append a new replica slot (scale-up / replacement capacity)."""
        with self._lock:
            self.replicas.append(replica)
            idx = self.core.add_replica()
        with self._stats_lock:
            self._stats.append(None)
        self._m_healthy.set(self.core.healthy_count())
        return idx

    def replica_keys(self) -> Set[str]:
        with self._lock:
            return {getattr(r, "key", None) for r in self.replicas}

    # ---- stats + health probing ------------------------------------------

    def fresh_stats(self, force: bool = False) -> List[Optional[Dict]]:
        now = time.monotonic()
        with self._stats_lock:
            if not force and now - self._stats_t < self.STATS_TTL_S:
                return self._stats
            self._stats_t = now
        stats: List[Optional[Dict]] = []
        for i, r in enumerate(list(self.replicas)):
            if not self.core.is_healthy(i):
                stats.append(None)
                continue
            try:
                s = r.call("engine_stats", _timeout=self.STATS_TIMEOUT_S)
                self.core.note_success(i)
            except Exception as e:
                # Staleness accrues per failed probe; fail_threshold
                # consecutive misses ejects (the replica stopped answering
                # the cheapest call it serves).
                s = None
                if self.core.note_failure(i):
                    self.eject_replica(i, reason=f"stats probe: {e!r:.80}")
            stats.append(s)
        with self._stats_lock:
            self._stats = stats
        return stats

    def _handoff_addr(self, idx: int):
        addr = self._handoff_addrs.get(idx)
        if addr is None:
            addr = self.replicas[idx].call("handoff_address")
            self._handoff_addrs[idx] = addr
        return addr

    # ---- ejection + migration --------------------------------------------

    def eject_replica(self, idx: int, *, reason: str = "") -> Optional[Dict]:
        """Health verdict: the replica is dead. Prune its affinity state,
        stop routing to it, emit LLM_REPLICA_EJECTED. Idempotent."""
        pruned = self.core.eject(idx)
        if pruned is None:
            return None
        self._handoff_addrs.pop(idx, None)
        self._m_healthy.set(self.core.healthy_count())
        # Same-tick cluster-table hygiene, mirroring the owner-LRU prune
        # above: blank the dead replica's live-owner hints in the GCS
        # prefix table so no lookup routes a request at the corpse. The
        # rows themselves stay — the pages are GCS-homed and adoptable by
        # any survivor (that is the point of the store).
        if self.prefix_store is not None:
            with self._stats_lock:
                s = self._stats[idx] if idx < len(self._stats) else None
            tag = (s or {}).get("replica") or ""
            if tag:
                try:
                    self.prefix_store.purge(owner_replica=tag,
                                            clear_owner_only=True)
                except Exception:
                    pass
        from ray_tpu.runtime import events

        events.emit(
            events.LLM_REPLICA_EJECTED,
            f"replica {idx} ejected: {reason or 'unhealthy'} "
            f"({pruned['prefix_pruned']} prefix + "
            f"{pruned['sessions_pruned']} session affinity entries pruned)",
            severity=events.WARNING, source="llm-router",
            labels={"replica": str(idx), "deployment": self.deployment,
                    "reason": reason[:120],
                    **{k: str(v) for k, v in pruned.items()}})
        return pruned

    def pick_migration_target(self, exclude: int) -> Optional[int]:
        """Least-loaded routable replica other than `exclude`."""
        stats = self.fresh_stats()
        best, best_score = None, None
        for i in range(self.core.n):
            if i == exclude or not self.core.is_routable(i):
                continue
            score = self.core.load_score(i, stats)
            if best_score is None or score < best_score:
                best, best_score = i, score
        return best

    def drain_replica(self, idx: int, *, reason: str = "node-draining",
                      target: Optional[int] = None) -> Dict:
        """Retire a replica gracefully: stop routing to it, migrate its
        live sessions (KV pages over the raw-frame wire) to the least-
        loaded survivor, and remap affinity so follow-ups land where the
        pages went. Safe to call twice (second call finds nothing live)."""
        if not self.core.is_healthy(idx):
            return {"migrated": [], "replayed": [], "target": None}
        self.core.set_draining(idx)
        if target is None or not self.core.is_routable(target):
            target = self.pick_migration_target(idx)
        if target is None:
            # Nowhere to migrate (last replica standing): it keeps its
            # sessions and keeps draining — the deadline kill will surface
            # as failures and the requests replay when capacity returns.
            return {"migrated": [], "replayed": [], "target": None}
        self._drain_target[idx] = target
        # Live sessions move FIRST: migrate_sessions quiesces admission and
        # snapshots the in-flight set, so the sessions decoding at the
        # moment the drain lands are the ones that travel with their KV.
        # Streaming the warm working set before this opened a window of
        # hundreds of ms in which fast-cycling sessions finished and their
        # affinity-pinned successors were admitted mid-prefill — the live
        # capture then found nothing to migrate and replayed everything.
        try:
            addr = self._handoff_addr(target)
            summary = self.replicas[idx].call("migrate_sessions", addr)
        except Exception as e:
            # The draining replica died before/while exporting: the wire's
            # atomicity means nothing half-adopted; consumers' blocked
            # calls fail and ride the failover replay path.
            self.eject_replica(idx, reason=f"died during drain: {e!r:.60}")
            return {"migrated": [], "replayed": [], "target": target,
                    "error": repr(e)}
        # Working-set handoff second: stream the victim's hottest reusable
        # prefix pages to the target so the fleet's shared prompts stay
        # warm across the drain (the successor serves them with zero
        # re-prefill). Best-effort — these pages were already spill
        # candidates, a failed push costs nothing.
        try:
            self.replicas[idx].call("push_prefixes",
                                    self._handoff_addr(target))
        except Exception:
            pass
        for rid in summary.get("send_failed", ()):
            # A migration send that failed with a lost ack may have left
            # the session adopted on the target — decoding with no
            # consumer, its KV pages pinned — while the replay path
            # re-submits it from the prompt. Abort the potential orphan
            # first (idempotent no-op when nothing was adopted).
            try:
                self.replicas[target].call("abort", rid, _timeout=5.0)
            except Exception:
                pass
        remapped = self.core.remap(idx, target)
        n = len(summary.get("migrated", ()))
        if n:
            self.migrated_sessions += n
            self._m_migrated.inc(n)
        from ray_tpu.runtime import events

        events.emit(
            events.LLM_SESSION_MIGRATED,
            f"replica {idx} drained to {target}: {n} sessions migrated "
            f"with KV, {len(summary.get('replayed', ()))} replayed from "
            f"prompt ({reason})",
            severity=events.INFO, source="llm-router",
            labels={"from_replica": str(idx), "to_replica": str(target),
                    "deployment": self.deployment, "reason": reason,
                    "migrated": str(n),
                    "replayed": str(len(summary.get("replayed", ()))),
                    **{k: str(v) for k, v in remapped.items()}})
        summary = dict(summary)
        summary["target"] = target
        return summary

    # ---- node events (the drain plane) -----------------------------------

    def replicas_on_node(self, node_hex: str) -> List[int]:
        """Map a cluster node id to replica indexes via the node_id each
        replica reports in engine_stats()."""
        with self._stats_lock:
            stats = list(self._stats)
        return [i for i, s in enumerate(stats)
                if s and s.get("node_id") == node_hex
                and self.core.is_healthy(i)]

    def handle_node_event(self, ev: Dict) -> None:
        """React to one NODE_DRAINING / NODE_DEAD / NODE_PREEMPTED event:
        drain-migrate replicas on a draining node, eject replicas on a
        dead one (pruning their affinity eagerly — the leak fix covers
        the event path, not just call failures)."""
        from ray_tpu.runtime import events as ev_mod

        node_hex = ev.get("node_id")
        if not node_hex:
            return
        for idx in self.replicas_on_node(node_hex):
            if ev.get("type") == ev_mod.NODE_DRAINING:
                self.drain_replica(idx, reason=f"node {node_hex[:8]} "
                                               "draining")
            else:  # NODE_DEAD / NODE_PREEMPTED
                self.eject_replica(idx, reason=f"node {node_hex[:8]} "
                                               f"{ev.get('type')}")

    def check_events(self, list_events_fn: Optional[Callable] = None) -> int:
        """Poll the cluster event ring for drain-plane events newer than
        the last poll; returns how many were handled."""
        from ray_tpu.runtime import events as ev_mod

        if list_events_fn is None:
            from ray_tpu.state import list_cluster_events as list_events_fn
        try:
            evs = list_events_fn(limit=200)
        except Exception:
            return 0
        interesting = [e for e in evs
                       if e.get("time", 0.0) > self._events_since
                       and e.get("type") in (ev_mod.NODE_DRAINING,
                                             ev_mod.NODE_DEAD,
                                             ev_mod.NODE_PREEMPTED)]
        if not interesting:
            return 0
        self._events_since = max(e["time"] for e in interesting)
        for e in sorted(interesting, key=lambda e: e.get("time", 0.0)):
            try:
                self.handle_node_event(e)
            except Exception:
                pass
        return len(interesting)

    # ---- replica-count policy --------------------------------------------

    def scale_tick(self, now: Optional[float] = None) -> Optional[Dict]:
        """One policy evaluation: compute the desired replica count from
        queue-delay/KV-pressure signals and act — scale-up through the
        injected callback, scale-down as drain-then-migrate-then-retire
        of the least-loaded replica. No-op without a policy."""
        if self.policy is None:
            return None
        now = time.monotonic() if now is None else now
        stats = self.fresh_stats()
        routable = [i for i in range(self.core.n)
                    if self.core.is_routable(i)]
        current = len(routable)
        if current == 0:
            return None
        desired = self.policy.desired(
            [stats[i] if i < len(stats) else None for i in routable],
            current, now)
        if desired == current:
            return None
        from ray_tpu.runtime import events

        if desired > current:
            if self._scale_up_fn is None:
                return None
            self._scale_up_fn(desired - current)
            events.emit(
                events.LLM_REPLICAS_SCALED,
                f"scale-up {current} -> {desired} (queue delay / KV "
                "pressure over target)",
                severity=events.INFO, source="llm-router",
                labels={"deployment": self.deployment,
                        "from": str(current), "to": str(desired),
                        "direction": "up"})
            return {"direction": "up", "from": current, "to": desired}
        # Scale-down: one replica per tick, the least-loaded one, and ONLY
        # via the drain plane — its sessions migrate before the actor dies.
        victim = min(routable, key=lambda i: self.core.load_score(i, stats))
        summary = self.drain_replica(victim, reason="scale-down")
        self.core.eject(victim)  # retired, not dead: no EJECTED event
        self._handoff_addrs.pop(victim, None)
        self._m_healthy.set(self.core.healthy_count())
        if self._retire_fn is not None:
            try:
                self._retire_fn(victim)
            except Exception:
                pass
        events.emit(
            events.LLM_REPLICAS_SCALED,
            f"scale-down {current} -> {current - 1}: replica {victim} "
            f"drained ({len(summary.get('migrated', ()))} sessions "
            "migrated) and retired",
            severity=events.INFO, source="llm-router",
            labels={"deployment": self.deployment, "from": str(current),
                    "to": str(current - 1), "direction": "down",
                    "victim": str(victim)})
        return {"direction": "down", "from": current, "to": current - 1,
                "victim": victim, "drain": summary}

    # ---- request path ----------------------------------------------------

    def completions(self, request: Dict) -> Dict:
        """Route one completion with failover: the request gets a stable
        router-assigned request_id, so any replay — replica death, drain
        fallback — reproduces the identical token stream (the engine seeds
        sampling from crc32(request_id) when no explicit seed is given).

        The router owns the request's ROOT trace span: the trace id derives
        from the rid (util/tracing.request_trace_id), so every span any
        replica records for this request — engine lifecycle, disagg
        handoff, migration pause — stitches under one trace with no context
        riding the RPCs themselves."""
        from ray_tpu.util import tracing

        request = dict(request)
        rid = request.get("request_id") or uuid.uuid4().hex[:12]
        request["request_id"] = rid
        with tracing.trace_context(tracing.request_trace_id(rid), None):
            with tracing.span("llm:request", "llm", request_id=rid,
                              deployment=self.deployment):
                return self._route_completions(request, rid)

    def _route_completions(self, request: Dict, rid: str) -> Dict:
        from ray_tpu.runtime import events, metric_defs
        from ray_tpu.util import tracing

        prompt = request.get("prompt", [])
        token_prompt = (list(prompt.encode()) if isinstance(prompt, str)
                        else list(prompt))
        tried: Set[int] = set()
        first_attempt = True
        while True:
            stats = self.fresh_stats()
            try:
                idx, decision = self.core.pick(
                    token_prompt, session_id=request.get("session_id"),
                    lora_name=request.get("lora_name"), stats=stats,
                    exclude=tried)
            except NoHealthyReplicasError as e:
                return {"error": {"code": 503, "type": "no_healthy_replicas",
                                  "message": str(e)}}
            if decision["reason"] == "pow2" and self.prefix_store is not None:
                # Local owner-LRU miss: the cluster prefix table remembers
                # owners across router restarts and owner ejection. A live
                # owner hint re-establishes local affinity; no hint is fine
                # — the pages are GCS-homed, so whoever we picked adopts
                # them from the store instead of re-prefilling.
                better = self._cluster_affinity(token_prompt, request,
                                                tried)
                if better is not None:
                    idx = better
            if first_attempt:
                # Admission gates the FIRST attempt only: a failover replay
                # has already consumed prefill work somewhere — shedding it
                # now would turn a survivable fault into a client error.
                metric_defs.LLM_ROUTER_AFFINITY.inc(tags={
                    "outcome": "hit" if decision["reason"] != "pow2"
                    else "miss"})
                t_adm = time.time()
                ok, projected = self.core.admit(idx, len(token_prompt),
                                                stats)
                tracing.record_span(
                    "llm:admit", "llm", t_adm, time.time(),
                    request_id=rid, replica=str(idx), admitted=ok,
                    projected_ttft_s=round(projected, 4))
                if not ok:
                    metric_defs.LLM_ROUTER_SHED.inc(
                        tags={"deployment": self.deployment})
                    events.emit(
                        events.LLM_REQUEST_SHED,
                        f"shed: projected TTFT {projected:.2f}s > SLO "
                        f"{self.core.slo_ttft_s:.2f}s",
                        severity=events.WARNING, source="llm-router",
                        labels={"projected_ttft_s": f"{projected:.3f}",
                                "slo_ttft_s":
                                    f"{self.core.slo_ttft_s:.3f}",
                                "replica": str(idx)})
                    return {"error": {
                        "code": 429, "type": "overloaded",
                        "message": "projected TTFT "
                                   f"{projected:.2f}s exceeds SLO; "
                                   "retry with backoff"}}
            first_attempt = False
            self.core.start(idx)
            try:
                if self.prefill_replicas:
                    return self._disagg_completions(request, idx,
                                                    token_prompt)
                t0 = time.monotonic()
                resp = self.replicas[idx].call("completions", request)
                self.core.observe_prefill(
                    len(token_prompt), max(time.monotonic() - t0, 1e-6))
                return resp
            except Exception as exc:
                t_fail = time.time()
                outcome = self._handle_request_failure(idx, rid, exc)
                if outcome is not None:
                    return outcome          # re-collected at drain target
                tracing.record_span(
                    "llm:failover_replay", "llm", t_fail, time.time(),
                    request_id=rid, replica=str(idx),
                    error=type(exc).__name__)
                tried.add(idx)
            finally:
                self.core.finish(idx)

    def _cluster_affinity(self, token_prompt: List[int], request: Dict,
                          tried: Set[int]) -> Optional[int]:
        """Map a cluster-table owner hint for this prompt's prefix back to
        a routable replica index (replica tags come from engine_stats).
        Returns None on miss, dead hint, or any store error — the pow2
        pick stands."""
        from ray_tpu.llm.prefix_store import cluster_chain

        try:
            chain = cluster_chain(token_prompt, self.core.block_size,
                                  request.get("lora_name") or "")
            if not chain:
                return None
            hit = self.prefix_store.lookup_owner(
                chain, lora_id=request.get("lora_name") or "")
        except Exception:
            return None
        if not hit or not hit.get("owner_replica"):
            return None
        with self._stats_lock:
            stats_now = list(self._stats)
        for i, s in enumerate(stats_now):
            if ((s or {}).get("replica") == hit["owner_replica"]
                    and i not in tried and self.core.is_routable(i)):
                # Re-seed the local owner-LRU so follow-ups skip the probe.
                self.core._remember(self.core.digest_chain(
                    token_prompt, request.get("lora_name")), i)
                return i
        return None

    def _disagg_completions(self, request: Dict, decode_idx: int,
                            token_prompt: List[int]) -> Dict:
        # Resolved BEFORE the prefill try: a dead decode replica fails
        # here and correctly rides the eject-and-replay path.
        from ray_tpu.util import tracing

        decode_addr = self._handoff_addr(decode_idx)
        t0 = time.monotonic()
        t0_wall = time.time()
        try:
            result = prefill_with_retry(self.prefill_replicas, request,
                                        decode_addr)
        except PrefillTierError as e:
            # Prefill-tier outage. The decode replica never saw this
            # request — ejecting it (the caller's failover path) would let
            # a transient prefill failure destroy the healthy decode fleet.
            # Every prefill replica was already retried; report upstream.
            # Deterministic app errors (bad request) are NOT caught here:
            # prefill_with_retry re-raises them and the caller's
            # _handle_request_failure propagates them to the client.
            return {"error": {
                "code": 503, "type": "prefill_unavailable",
                "message": f"prefill tier unavailable: {e}"}}
        if not result.get("handoff"):
            return result["response"]  # finished at prefill
        tracing.record_span(
            "llm:prefill_rpc", "llm", t0_wall, time.time(),
            request_id=request["request_id"], tokens=len(token_prompt),
            replica=str(decode_idx))
        self.core.observe_prefill(
            len(token_prompt), max(time.monotonic() - t0, 1e-6))
        return self.replicas[decode_idx].call(
            "completions_collect", result["rid"])

    def _handle_request_failure(self, idx: int, rid: str,
                                exc: BaseException) -> Optional[Dict]:
        """Classify a failed replica call. Returns a response when the
        request actually completed elsewhere (KV migration re-collect);
        None means the caller should replay the request on another
        replica; application errors re-raise to the client untouched."""
        from ray_tpu.runtime import events

        mode = _migrated_mode(exc)
        if mode == "kv":
            # The drain plane moved the live stream — including every token
            # already generated — to the adoptive replica; collect there.
            target = self._drain_target.get(idx)
            if target is not None and self.core.is_healthy(target):
                # The adopted stream is the target's work now: account it
                # in the target's in-flight so pow2 scoring sees it.
                self.core.start(target)
                try:
                    return self.replicas[target].call(
                        "completions_collect", rid)
                except Exception:
                    mode = "replay"  # target died too: replay from prompt
                finally:
                    self.core.finish(target)
            else:
                mode = "replay"
        if mode == "replay" or _is_draining_error(exc):
            # Drain-path fallback: the replica is retiring, not dead. The
            # replay is seeded-identical; no ejection, no failover event
            # (LLM_SESSION_MIGRATED already told the story).
            return None
        if not _is_transport_error(exc):
            # The replica executed the request and raised — a malformed
            # request, a per-request stream timeout, an engine error. It is
            # alive and healthy; ejecting it would let one bad request walk
            # the retry loop and take down the whole fleet. Propagate.
            raise exc
        # Hard failure: abort the orphan if the replica still answers (a
        # timed-out request must not keep decoding into dead KV pages),
        # then eject and replay on a survivor.
        try:
            self.replicas[idx].call("abort", rid, _timeout=5.0)
        except Exception:
            pass
        self.eject_replica(idx, reason=f"request call failed: {exc!r:.80}")
        self.failovers += 1
        self._m_failovers.inc()
        events.emit(
            events.LLM_REQUEST_FAILOVER,
            f"request {rid} replayed after replica {idx} failed "
            f"({type(exc).__name__}); seeded replay is token-identical",
            severity=events.WARNING, source="llm-router",
            labels={"request_id": rid, "replica": str(idx),
                    "deployment": self.deployment,
                    "error": repr(exc)[:120]})
        return None

    def stats_summary(self) -> Dict:
        return {
            "replicas": self.core.n,
            "healthy_replicas": self.core.healthy_count(),
            "routable_replicas": self.core.routable_count(),
            "prefill_replicas": len(self.prefill_replicas),
            "affinity_hits": self.core.affinity_hits,
            "affinity_misses": self.core.affinity_misses,
            "shed_count": self.core.shed_count,
            "failovers": self.failovers,
            "sessions_migrated": self.migrated_sessions,
            "replicas_ejected": self.core.ejected_count,
        }


class LLMRouter:
    """The serve deployment fronting the LLM fleet (build_routed_app).

    Requests: same body as LLMServer.completions plus optional
    "session_id". Responses: the completion dict, or a 429-shaped
    {"error": {"code": 429, ...}} when SLO admission sheds. All the
    routing/resilience logic lives in FleetSupervisor/RouterCore; this
    class resolves replica actor handles, runs the control loop (drain-
    plane event watcher + replica policy), and bridges scaling decisions
    to the ServeController."""

    STATS_TTL_S = FleetSupervisor.STATS_TTL_S
    CONTROL_INTERVAL_S = 1.0

    def __init__(self, llm_config, engine_deployment: str,
                 prefill_deployment: Optional[str] = None):
        self.config = llm_config
        self.deployment = engine_deployment
        self._prefill_deployment = prefill_deployment
        # Replica handles resolve on the FIRST REQUEST, not here: this
        # __init__ runs while the controller is still blocked deploying the
        # router itself, so calling back into it (get_replicas) from here
        # deadlocks until the get times out and kills the deploy.
        self.replicas: List[Any] = []
        self.prefill_replicas: List[Any] = []
        self.core: Optional[RouterCore] = None
        self.supervisor: Optional[FleetSupervisor] = None
        self._resolve_lock = threading.Lock()
        self._control_thread: Optional[threading.Thread] = None

    # ---- replica state ---------------------------------------------------

    def _ensure_replicas(self) -> None:
        if self.supervisor is not None:
            return
        with self._resolve_lock:
            if self.supervisor is not None:
                return
            from ray_tpu import serve

            handle = serve.get_deployment_handle(self.deployment)
            self.replicas = [
                ActorReplica(h, name=f"{self.deployment}#{i}")
                for i, h in enumerate(handle.replica_handles())]
            if self._prefill_deployment:
                ph = serve.get_deployment_handle(self._prefill_deployment)
                self.prefill_replicas = [
                    ActorReplica(h, name=f"{self._prefill_deployment}#{i}")
                    for i, h in enumerate(ph.replica_handles())]
            self.core = RouterCore(
                len(self.replicas), block_size=self.config.block_size,
                slo_ttft_s=self.config.slo_ttft_s)
            policy = None
            pol_cfg = getattr(self.config, "replica_policy", None)
            if pol_cfg is not None:
                from ray_tpu.llm.replica_policy import (
                    ReplicaPolicy, ReplicaPolicyConfig)

                if isinstance(pol_cfg, dict):
                    pol_cfg = ReplicaPolicyConfig(**pol_cfg)
                if not isinstance(pol_cfg, ReplicaPolicy):
                    pol_cfg = ReplicaPolicy(pol_cfg)
                policy = pol_cfg
            prefix_store = None
            if getattr(self.config, "cluster_prefix_store", False):
                try:
                    from ray_tpu.llm.prefix_store import ClusterPrefixStore

                    store = ClusterPrefixStore(self.config.block_size,
                                               deployment=self.deployment)
                    if store.available():
                        prefix_store = store
                except Exception:
                    prefix_store = None
            # supervisor is the publication barrier: assigned LAST, so a
            # racing reader that sees it non-None sees resolved state too.
            sup = FleetSupervisor(
                self.core, self.replicas, deployment=self.deployment,
                prefill_replicas=self.prefill_replicas, policy=policy,
                scale_up_fn=self._scale_up, retire_fn=self._retire,
                prefix_store=prefix_store)
            self.supervisor = sup
            self._start_control_loop()

    # ---- controller bridge (scale actions) -------------------------------

    def _controller(self):
        from ray_tpu.serve.api import _get_controller

        return _get_controller()

    def _scale_up(self, delta: int) -> None:
        import ray_tpu

        ctrl = self._controller()
        current = len(ray_tpu.get(
            ctrl.get_replicas.remote(self.deployment))["replicas"])
        ray_tpu.get(ctrl.scale_replicas.remote(
            self.deployment, current + int(delta)), timeout=300)
        self._sync_replicas()

    def _retire(self, idx: int) -> None:
        import ray_tpu

        replica = self.supervisor.replicas[idx]
        key = getattr(replica, "key", None)
        if key is None:
            return
        ray_tpu.get(self._controller().remove_replica.remote(
            self.deployment, key), timeout=60)

    def _sync_replicas(self) -> None:
        """Pick up replicas the controller added since resolution: new
        actor ids get fresh router slots (slots are append-only; removed
        replicas keep their dead slot)."""
        from ray_tpu import serve

        sup = self.supervisor
        if sup is None:
            return
        handle = serve.get_deployment_handle(self.deployment)
        known = sup.replica_keys()
        for h in handle.replica_handles():
            r = ActorReplica(h, name=f"{self.deployment}#?")
            if r.key not in known:
                idx = sup.add_replica(r)
                r.name = f"{self.deployment}#{idx}"

    # ---- control loop ----------------------------------------------------

    def _start_control_loop(self) -> None:
        if self._control_thread is not None:
            return
        t = threading.Thread(target=self._control_loop,
                             name="llm-router-control", daemon=True)
        self._control_thread = t
        t.start()

    def _control_loop(self) -> None:
        """Background fleet management: watch the drain plane and run the
        replica policy. Every step is best-effort — the request path never
        depends on this thread."""
        while True:
            time.sleep(self.CONTROL_INTERVAL_S)
            sup = self.supervisor
            if sup is None:
                continue
            try:
                sup.check_events()
            except Exception:
                pass
            try:
                sup.scale_tick()
            except Exception:
                pass
            try:
                self._sync_replicas()
            except Exception:
                pass

    # ---- API -------------------------------------------------------------

    def __call__(self, request: Dict) -> Dict:
        return self.completions(request)

    def completions(self, request: Dict) -> Dict:
        self._ensure_replicas()
        return self.supervisor.completions(request)

    def drain_replica(self, idx: int, *, reason: str = "manual") -> Dict:
        """Operator entry point: drain-migrate one replica now."""
        self._ensure_replicas()
        return self.supervisor.drain_replica(idx, reason=reason)

    def router_stats(self) -> Dict:
        self._ensure_replicas()
        return self.supervisor.stats_summary()
