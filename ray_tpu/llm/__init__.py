from ray_tpu.llm.engine import LLMEngine, RequestOutput  # noqa: F401
from ray_tpu.llm.sampling import SamplingParams  # noqa: F401
from ray_tpu.llm.serving import (  # noqa: F401
    LLMConfig,
    LLMServer,
    build_llm_deployment,
    build_openai_app,
)
