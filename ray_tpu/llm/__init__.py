from ray_tpu.core.exceptions import WeightSyncError  # noqa: F401
from ray_tpu.llm.engine import (  # noqa: F401
    LLMEngine,
    RequestOutput,
    prefix_digest_chain,
)
from ray_tpu.llm.sampling import SamplingParams  # noqa: F401
from ray_tpu.llm.serving import (  # noqa: F401
    LLMConfig,
    LLMServer,
    RequestTimeoutError,
    build_engine,
    build_llm_deployment,
    build_openai_app,
    build_routed_app,
)

__all__ = [
    "LLMEngine", "RequestOutput", "WeightSyncError", "prefix_digest_chain",
    "SamplingParams",
    "LLMConfig", "LLMServer", "RequestTimeoutError", "build_engine",
    "build_llm_deployment", "build_openai_app", "build_routed_app",
]


def __getattr__(name):
    # Router/disagg classes import lazily: they pull in the collective
    # transport stack, which most llm users never touch.
    if name in ("RouterCore", "LLMRouter", "LocalReplica", "ActorReplica",
                "prefill_with_retry"):
        import ray_tpu.llm.router as _r

        return getattr(_r, name)
    if name in ("PrefillServer", "KVStreamServer", "HandoffError",
                "send_handoff"):
        import ray_tpu.llm.disagg as _d

        return getattr(_d, name)
    raise AttributeError(name)
