"""Multi-LoRA serving: batched low-rank adapters over the paged engine.

Reference analog: the LoRA multiplex deployments under
python/ray/llm/_internal/serve/deployments/llm/multiplex/ (the reference
delegates the actual multi-LoRA math to vLLM/punica CUDA kernels). TPU-native
design: adapters live in STACKED device tensors per target projection —
    A: (n_layers, n_slots, d_in, rank)   B: (n_layers, n_slots, rank, d_out)
and every sequence in a batch carries a slot index; the per-layer delta is
two gathered einsums
    delta[s] = (x[s] @ A[l, slot(s)]) @ B[l, slot(s)] * (alpha / rank)
— batched over the whole mixed-adapter batch, MXU-shaped, no per-request
recompiles (slot 0 is the identity/zero adapter, i.e. the base model).
Slot management is LRU: load_adapter evicts the least-recently-used slot
when full (the serve.multiplex policy, collapsed into the runner).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Projections that may carry adapters, with (in, out) dims per config.
TARGETS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def target_dims(config) -> Dict[str, Tuple[int, int]]:
    d, f, hd = config.d_model, config.d_ff, config.head_dim
    return {
        "wq": (d, config.n_heads * hd),
        "wk": (d, config.n_kv_heads * hd),
        "wv": (d, config.n_kv_heads * hd),
        "wo": (config.n_heads * hd, d),
        "w_gate": (d, f),
        "w_up": (d, f),
        "w_down": (f, d),
    }


@dataclasses.dataclass
class LoRAAdapter:
    """One adapter: per-target stacked factors over layers.

    weights[target] = (A, B) with A: (n_layers, d_in, rank),
    B: (n_layers, rank, d_out). Missing targets mean identity."""

    name: str
    rank: int
    alpha: float
    weights: Dict[str, Tuple[np.ndarray, np.ndarray]]

    @property
    def scaling(self) -> float:
        return self.alpha / max(self.rank, 1)


def init_adapter(config, name: str, rank: int = 8, alpha: float = 16.0,
                 targets: Sequence[str] = ("wq", "wv"), key=None,
                 scale: float = 0.1) -> LoRAAdapter:
    """Random A, random-small B (standard LoRA init uses zero B; tests use a
    nonzero scale so adapters measurably change logits)."""
    key = key if key is not None else jax.random.key(abs(hash(name)) % (2**31))
    dims = target_dims(config)
    weights = {}
    for i, t in enumerate(targets):
        d_in, d_out = dims[t]
        ka, kb = jax.random.split(jax.random.fold_in(key, i))
        a = jax.random.normal(ka, (config.n_layers, d_in, rank),
                              dtype=jnp.float32) / np.sqrt(d_in)
        b = (jax.random.normal(kb, (config.n_layers, rank, d_out),
                               dtype=jnp.float32) / np.sqrt(rank)) * scale
        weights[t] = (np.asarray(a), np.asarray(b))
    return LoRAAdapter(name, rank, alpha, weights)


class LoRAManager:
    """Owns the stacked slot tensors + name->slot LRU table.

    Slot 0 is permanently the zero adapter (base model); user adapters
    occupy slots 1..n_slots-1. All adapters in one manager share `rank`
    (pad smaller ranks with zeros when loading)."""

    def __init__(self, config, n_slots: int = 8, rank: int = 8,
                 targets: Sequence[str] = TARGETS, dtype=None):
        self.config = config
        self.n_slots = n_slots + 1          # +1 for the base slot
        self.rank = rank
        self.targets = tuple(targets)
        self.dtype = dtype or config.dtype
        dims = target_dims(config)
        L = config.n_layers
        self.stacks = {}
        for t in self.targets:
            d_in, d_out = dims[t]
            self.stacks[t] = (
                jnp.zeros((L, self.n_slots, d_in, rank), dtype=self.dtype),
                jnp.zeros((L, self.n_slots, rank, d_out), dtype=self.dtype))
        # name -> slot; slot use ticks for LRU
        self._slots: Dict[str, int] = {}
        self._scaling: Dict[int, float] = {}
        self._tick = 0
        self._last_used: Dict[int, int] = {}
        # slot -> count of queued/in-flight requests using it: a pinned
        # slot must never be evicted (an LRU reuse would silently switch
        # a running sequence's adapter mid-generation).
        self._pins: Dict[int, int] = {}
        # Pool-scaling telemetry (engine stats() -> LoRAPoolPolicy): loads
        # count every load_adapter install; evictions count only the
        # LRU-eviction subset — a persistently nonzero eviction rate is
        # the "pool too small" signal.
        self.loads = 0
        self.evictions = 0

    def lora_pytree(self) -> Dict:
        """The stacks, passed into the jitted step (a dict pytree whose
        leaves have leading dim n_layers, so lax.scan slices per layer)."""
        return {t: {"a": a, "b": b} for t, (a, b) in self.stacks.items()}

    def slot_of(self, name: Optional[str]) -> int:
        if not name:
            return 0
        if name not in self._slots:
            raise KeyError(f"LoRA adapter {name!r} not loaded")
        slot = self._slots[name]
        self._tick += 1
        self._last_used[slot] = self._tick
        return slot

    def pin(self, slot: int):
        """Mark a slot as referenced by a queued/running request."""
        if slot:
            self._pins[slot] = self._pins.get(slot, 0) + 1

    def unpin(self, slot: int):
        if not slot:
            return
        n = self._pins.get(slot, 0) - 1
        if n > 0:
            self._pins[slot] = n
        else:
            self._pins.pop(slot, None)

    @property
    def loaded(self) -> List[str]:
        return sorted(self._slots)

    def name_of(self, slot: int) -> Optional[str]:
        """Inverse of slot_of: the adapter currently holding `slot`
        ("" for the base slot, None for an empty/unknown slot). The prefix
        store keys spilled KV by adapter NAME, not slot — slots are
        recycled across loads, names are identity."""
        if slot == 0:
            return ""
        for name, s in self._slots.items():
            if s == slot:
                return name
        return None

    def load_adapter(self, adapter: LoRAAdapter) -> int:
        """Install (or refresh) an adapter; returns its slot. Evicts the
        LRU adapter when all user slots are taken."""
        if adapter.rank > self.rank:
            raise ValueError(
                f"adapter rank {adapter.rank} > manager rank {self.rank}")
        if adapter.name in self._slots:
            slot = self._slots[adapter.name]
            if self._pins.get(slot):
                # Refreshing a live slot would switch a running sequence's
                # adapter weights mid-generation — the same hazard pinning
                # guards against on the eviction path.
                raise RuntimeError(
                    f"LoRA adapter {adapter.name!r} is referenced by "
                    "in-flight requests; retry the refresh once they drain")
        elif len(self._slots) < self.n_slots - 1:
            used = set(self._slots.values())
            slot = next(s for s in range(1, self.n_slots) if s not in used)
        else:
            evictable = [s for s in self._slots.values()
                         if not self._pins.get(s)]
            if not evictable:
                raise RuntimeError(
                    "all LoRA slots are referenced by in-flight requests; "
                    "cannot load a new adapter (raise n_slots)")
            slot = min(evictable, key=lambda s: self._last_used.get(s, 0))
            evicted = next(n for n, s in self._slots.items() if s == slot)
            del self._slots[evicted]
            self.evictions += 1
        self.loads += 1
        self._slots[adapter.name] = slot
        self._tick += 1
        self._last_used[slot] = self._tick
        scaling = adapter.scaling
        self._scaling[slot] = scaling
        for t in self.targets:
            a_stack, b_stack = self.stacks[t]
            if t in adapter.weights:
                a, b = adapter.weights[t]
                r = a.shape[-1]
                a_pad = np.zeros((a_stack.shape[0], a_stack.shape[2],
                                  self.rank), dtype=np.float32)
                a_pad[:, :, :r] = np.asarray(a, dtype=np.float32)
                b_pad = np.zeros((b_stack.shape[0], self.rank,
                                  b_stack.shape[3]), dtype=np.float32)
                # Fold the alpha/rank scaling into B so the kernel needs no
                # per-slot scale lookup.
                b_pad[:, :r, :] = np.asarray(b, dtype=np.float32) * scaling
            else:
                a_pad = np.zeros((a_stack.shape[0], a_stack.shape[2],
                                  self.rank), dtype=np.float32)
                b_pad = np.zeros((b_stack.shape[0], self.rank,
                                  b_stack.shape[3]), dtype=np.float32)
            self.stacks[t] = (
                a_stack.at[:, slot].set(jnp.asarray(a_pad, dtype=self.dtype)),
                b_stack.at[:, slot].set(jnp.asarray(b_pad, dtype=self.dtype)))
        return slot

    def resize(self, n_slots: int) -> int:
        """Grow/shrink the user-slot pool in place (LoRAPoolPolicy's
        actuator), rebuilding the stacked tensors and preserving every
        occupied slot column. Shrinks clamp to the highest loaded or
        pinned slot index — a resize must never orphan a resident adapter
        or yank one out from under an in-flight request. Returns the new
        user-slot count. (The stacks change shape, so the next step pays
        one recompile — the policy's cooldown keeps that rare.)"""
        floor = max([0] + list(self._slots.values())
                    + [s for s, n in self._pins.items() if n])
        new = max(int(n_slots), floor, 1)
        total = new + 1
        if total == self.n_slots:
            return new
        keep = min(self.n_slots, total)
        for t in self.targets:
            a_stack, b_stack = self.stacks[t]
            a_new = jnp.zeros(
                (a_stack.shape[0], total) + a_stack.shape[2:],
                dtype=self.dtype)
            b_new = jnp.zeros(
                (b_stack.shape[0], total) + b_stack.shape[2:],
                dtype=self.dtype)
            self.stacks[t] = (a_new.at[:, :keep].set(a_stack[:, :keep]),
                              b_new.at[:, :keep].set(b_stack[:, :keep]))
        self.n_slots = total
        return new


@dataclasses.dataclass
class LoRAPoolPolicyConfig:
    """Watermarks for adapter-pool scaling (ReplicaPolicyConfig analog)."""

    min_slots: int = 1
    max_slots: int = 32
    high_occupancy: float = 0.9   # loaded/slots at or above -> grow
    low_occupancy: float = 0.5    # at or below (sustained) -> shrink
    grow_factor: float = 1.5
    cooldown_s: float = 10.0      # min seconds between resizes
    quiet_s: float = 30.0         # sustained low occupancy before a shrink


class LoRAPoolPolicy:
    """Adapter-pool scaling off the same engine_stats() telemetry that
    drives ReplicaPolicy (llm/replica_policy.py), one level down: instead
    of replicas, the actuator is LoRAManager.resize. Pure and clock-driven
    — feed it stats dicts + `now`, it answers a desired slot count or None
    (serving.LLMServer ticks it from the engine loop).

    Grow on pressure: occupancy at the high watermark, or any LRU eviction
    since the last tick (an eviction means a wanted adapter was pushed out
    — occupancy alone can't see that once the pool pins full). Shrink only
    after a sustained quiet window, never below what is loaded/pinned."""

    def __init__(self, config: Optional[LoRAPoolPolicyConfig] = None):
        self.config = config or LoRAPoolPolicyConfig()
        self._last_resize = 0.0
        self._quiet_since: Optional[float] = None
        self._last_evictions = 0

    def desired(self, stats: dict, now: float) -> Optional[int]:
        cfg = self.config
        slots = int(stats.get("lora_slots", 0))
        if slots <= 0:
            return None
        loaded = int(stats.get("lora_loaded", 0))
        evictions = int(stats.get("lora_evictions", 0))
        evicting = evictions > self._last_evictions
        self._last_evictions = evictions
        occupancy = loaded / slots
        if now - self._last_resize < cfg.cooldown_s:
            return None
        if ((evicting or occupancy >= cfg.high_occupancy)
                and slots < cfg.max_slots):
            self._quiet_since = None
            self._last_resize = now
            return min(cfg.max_slots,
                       max(slots + 1, int(slots * cfg.grow_factor)))
        if occupancy <= cfg.low_occupancy and slots > cfg.min_slots:
            if self._quiet_since is None:
                self._quiet_since = now
            elif now - self._quiet_since >= cfg.quiet_s:
                self._quiet_since = None
                self._last_resize = now
                return max(cfg.min_slots, loaded, slots // 2, 1)
        else:
            self._quiet_since = None
        return None


def apply_lora(x: jax.Array, lA: jax.Array, lB: jax.Array,
               lora_idx: jax.Array) -> jax.Array:
    """Batched delta for one layer's one target.

    x: (S, Bq, d_in); lA: (n_slots, d_in, r); lB: (n_slots, r, d_out);
    lora_idx: (S,) int32 slot per sequence. Returns (S, Bq, d_out)."""
    a_sel = lA[lora_idx]            # (S, d_in, r)
    b_sel = lB[lora_idx]            # (S, r, d_out)
    mid = jnp.einsum("sbd,sdr->sbr", x, a_sel)
    return jnp.einsum("sbr,sro->sbo", mid, b_sel)
