"""LLM serving deployment: OpenAI-style completions over the native engine.

Reference analog: python/ray/llm/_internal/serve/ (VLLMEngine wrapper
vllm_engine.py:222, vllm_deployment.py, the OpenAI router deployments/
routers/, build_openai_app). Ours wraps the native paged-attention engine
(ray_tpu.llm.engine) in a serve deployment. The engine loop runs on a
background thread inside the replica (the vLLM MQEngine pattern collapsed
in-process): request threads enqueue prompts and consume per-request token
queues, so many requests stream concurrently through one continuously-
batched engine. TP maps to a mesh inside the replica (SERVE_RULES sharding),
placed via num_tpus — the reference plans TP x PP placement groups around
vLLM (vllm_models.py:117-168).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from ray_tpu import serve


@dataclasses.dataclass
class LLMConfig:
    model_config: Any = None            # llama.LlamaConfig
    params_checkpoint: Optional[str] = None  # dir with saved params pytree
    seed: int = 0
    num_kv_blocks: int = 256
    block_size: int = 16
    max_batch_size: int = 8
    num_replicas: int = 1
    num_tpus_per_replica: float = 0.0
    tensor_parallel: int = 1            # tp axis size of the in-replica mesh
    prefill_chunk: int = 128
    tokenizer: Any = None
    # Multi-LoRA (llm/lora.py): preloaded adapters + slot-table sizing.
    lora_adapters: Any = None           # list[LoRAAdapter] | None
    max_loras: int = 8
    lora_rank: int = 8
    # Engine features (llm/engine.py): automatic prefix caching (shared
    # system prompts skip prefill) and n-gram speculative decoding
    # (greedy-only; tokens proposed from the sequence's own history).
    enable_prefix_caching: bool = True
    speculative_ngram: int = 0
    # Multi-step decode: one dispatch generates k tokens via an on-device
    # scan (engine.py) — the decode-throughput lever when dispatch latency
    # rivals per-token compute (remote-attached TPUs).
    decode_multi_step: int = 1
    # Precompile step buckets at replica start so user requests don't pay
    # XLA compiles mid-stream (vLLM-TPU startup precompile; a cold bucket
    # costs seconds of TTFT on multi-B-param models). "full" = whole
    # batch x chunk grid incl. the host-logits path (minutes of startup
    # compiles on big models, zero mid-stream stalls); "light" = the
    # sequential-traffic set (fast startup, batched-prefill shapes still
    # compile on first hit); "off" = lazy. True/False alias full/off.
    warmup_buckets: Any = "full"


class LLMServer:
    """The replica callable: owns one engine instance + its step loop."""

    def __init__(self, llm_config: LLMConfig):
        import jax

        from ray_tpu.llm.engine import LLMEngine
        from ray_tpu.llm.model_runner import ModelRunner
        from ray_tpu.models import llama

        config = llm_config.model_config or llama.LlamaConfig.tiny()
        if llm_config.params_checkpoint:
            from ray_tpu.train.checkpoint import Checkpoint

            params = Checkpoint(llm_config.params_checkpoint).load_pytree()
        else:
            params = llama.init_params(config, jax.random.key(llm_config.seed))
        mesh = None
        if llm_config.tensor_parallel > 1:
            from ray_tpu.parallel.mesh import MeshConfig, build_mesh

            mesh = build_mesh(
                MeshConfig(tp=llm_config.tensor_parallel),
                devices=jax.devices()[:llm_config.tensor_parallel])
        lora_manager = None
        if llm_config.lora_adapters:
            from ray_tpu.llm.lora import LoRAManager

            lora_manager = LoRAManager(config, n_slots=llm_config.max_loras,
                                       rank=llm_config.lora_rank)
            for adapter in llm_config.lora_adapters:
                lora_manager.load_adapter(adapter)
        runner = ModelRunner(config, params,
                             num_blocks=llm_config.num_kv_blocks,
                             block_size=llm_config.block_size,
                             chunk_size=llm_config.prefill_chunk,
                             mesh=mesh, lora_manager=lora_manager)
        self.engine = LLMEngine(
            runner, max_batch_size=llm_config.max_batch_size,
            tokenizer=llm_config.tokenizer,
            prefill_chunk=llm_config.prefill_chunk,
            enable_prefix_caching=llm_config.enable_prefix_caching,
            speculative_ngram=llm_config.speculative_ngram,
            decode_multi_step=llm_config.decode_multi_step)
        wm = llm_config.warmup_buckets
        wm = {True: "full", False: "off"}.get(wm, wm)
        if wm not in ("off", "light", "full"):
            raise ValueError(f"warmup_buckets: {wm!r} not off/light/full")
        if wm != "off":
            self.engine.warmup(full=wm == "full")
        self.tokenizer = llm_config.tokenizer
        self._lock = threading.Lock()
        # request_id -> per-request event queue; the engine loop fans
        # RequestOutputs out to these (token-at-a-time streaming).
        self._streams: Dict[str, queue.Queue] = {}
        self._loop = threading.Thread(target=self._engine_loop, daemon=True)
        self._loop.start()

    # ---- engine loop -----------------------------------------------------

    def _engine_loop(self):
        import logging

        log = logging.getLogger(__name__)
        while True:
            try:
                with self._lock:
                    busy = self.engine.has_unfinished()
                    outs = self.engine.step() if busy else []
            except Exception as e:
                # A wedged engine must not silently strand every request:
                # surface the failure to all waiters and reset to a clean
                # scheduler state.
                log.exception("engine step failed; failing active requests")
                with self._lock:
                    import numpy as _np

                    # Drain in-flight device steps BEFORE freeing their
                    # pages (late writes into recycled pages would corrupt
                    # future sequences), then force-release everything.
                    for flight in list(self.engine._flights):
                        try:
                            _np.asarray(flight["tokens"])
                        except Exception:
                            pass
                    self.engine._flights.clear()
                    for req, blocks in self.engine._pending_release:
                        self.engine.block_manager.release_blocks(blocks)
                    self.engine._pending_release.clear()
                    for req in (list(self.engine.running)
                                + list(self.engine.prefilling)
                                + list(self.engine.waiting)):
                        req.dispatched = 0
                        self.engine.block_manager.release(req)
                    self.engine.running.clear()
                    self.engine.prefilling.clear()
                    self.engine.waiting.clear()
                for q in list(self._streams.values()):
                    q.put(e)
                continue
            for out in outs:
                q = self._streams.get(out.request_id)
                if q is not None:
                    q.put(out)
            if not busy:
                time.sleep(0.005)

    def _submit(self, prompt, params, lora_name=None) -> str:
        rid = uuid.uuid4().hex[:12]
        q: queue.Queue = queue.Queue()
        self._streams[rid] = q
        try:
            with self._lock:
                self.engine.add_request(prompt, params, request_id=rid,
                                        lora_name=lora_name)
        except Exception:
            self._streams.pop(rid, None)
            raise
        return rid

    def _parse(self, request: Dict):
        from ray_tpu.llm.sampling import SamplingParams

        prompt = request.get("prompt", [])
        if isinstance(prompt, str):
            if self.tokenizer is None:
                raise ValueError("string prompts require a tokenizer")
            prompt = self.tokenizer.encode(prompt)
        params = SamplingParams(
            temperature=float(request.get("temperature", 0.0)),
            top_k=int(request.get("top_k", 0)),
            top_p=float(request.get("top_p", 1.0)),
            max_tokens=int(request.get("max_tokens", 32)),
            stop_token_ids=request.get("stop_token_ids"),
            seed=request.get("seed"))
        return prompt, params, request.get("lora_name")

    # ---- LoRA management (multiplex) ------------------------------------

    def load_lora_adapter(self, adapter) -> Dict:
        """Dynamically install a LoRAAdapter (LRU-evicting when full)."""
        if self.engine.runner.lora is None:
            raise ValueError("replica built without LoRA support "
                             "(set LLMConfig.lora_adapters)")
        with self._lock:
            slot = self.engine.runner.lora.load_adapter(adapter)
        return {"name": adapter.name, "slot": slot}

    def list_lora_adapters(self) -> Dict:
        mgr = self.engine.runner.lora
        return {"adapters": mgr.loaded if mgr is not None else []}

    # ---- API -------------------------------------------------------------

    def __call__(self, request: Dict) -> Dict:
        return self.completions(request)

    def completions(self, request: Dict) -> Dict:
        """OpenAI-ish /v1/completions: {"prompt": str|[int], "max_tokens",
        "temperature", "top_k", "top_p", "stop_token_ids"}."""
        prompt, params, lora_name = self._parse(request)
        rid = self._submit(prompt, params, lora_name)
        q = self._streams[rid]
        try:
            while True:
                out = q.get(timeout=300)
                if isinstance(out, Exception):
                    raise out
                if out.finished:
                    break
        finally:
            self._streams.pop(rid, None)
        return {
            "id": out.request_id,
            "object": "text_completion",
            "choices": [{
                "text": out.text,
                "token_ids": out.output_token_ids,
                "finish_reason": out.finish_reason,
            }],
            "usage": {
                "prompt_tokens": len(out.prompt_token_ids),
                "completion_tokens": len(out.output_token_ids),
            },
        }

    def completions_stream(self, request: Dict):
        """Streaming completions: a generator of OpenAI-style chunk events,
        one per sampled token. Consume through
        handle.options("completions_stream").remote_stream(request)."""
        prompt, params, lora_name = self._parse(request)
        rid = self._submit(prompt, params, lora_name)
        q = self._streams[rid]
        try:
            while True:
                out = q.get(timeout=300)
                if isinstance(out, Exception):
                    raise out
                for t in out.new_token_ids:
                    yield {"id": rid, "object": "text_completion.chunk",
                           "token": int(t), "finished": False}
                if out.finished:
                    yield {"id": rid, "object": "text_completion.chunk",
                           "token": None, "finished": True,
                           "finish_reason": out.finish_reason,
                           "text": out.text,
                           "token_ids": out.output_token_ids}
                    return
        finally:
            self._streams.pop(rid, None)


def build_llm_deployment(llm_config: LLMConfig, name: str = "llm") -> Any:
    dep = serve.deployment(LLMServer).options(
        name=name, num_replicas=llm_config.num_replicas,
        num_tpus=llm_config.num_tpus_per_replica,
        max_ongoing_requests=llm_config.max_batch_size)
    return dep.bind(llm_config)


def build_openai_app(llm_config: LLMConfig, name: str = "v1-completions"):
    """Deploys the engine and the HTTP ingress; POST /{name} serves
    completions."""
    handle = serve.run(build_llm_deployment(llm_config, name), http=True)
    return handle
