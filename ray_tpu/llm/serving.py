"""LLM serving deployment: OpenAI-style completions over the native engine.

Reference analog: python/ray/llm/_internal/serve/ (VLLMEngine wrapper
vllm_engine.py:222, vllm_deployment.py, the OpenAI router deployments/
routers/, build_openai_app). Ours wraps the native paged-attention engine
(ray_tpu.llm.engine) in a serve deployment; TP placement maps to num_tpus on
the replica (the reference plans TP x PP placement groups around vLLM,
vllm_models.py:117-168 — here the engine's mesh lives inside the replica).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from ray_tpu import serve


@dataclasses.dataclass
class LLMConfig:
    model_config: Any = None            # llama.LlamaConfig
    params_checkpoint: Optional[str] = None  # dir with saved params pytree
    seed: int = 0
    num_kv_blocks: int = 256
    block_size: int = 16
    max_batch_size: int = 8
    num_replicas: int = 1
    num_tpus_per_replica: float = 0.0
    tokenizer: Any = None


class LLMServer:
    """The replica callable: owns one engine instance."""

    def __init__(self, llm_config: LLMConfig):
        import jax

        from ray_tpu.llm.engine import LLMEngine
        from ray_tpu.llm.model_runner import ModelRunner
        from ray_tpu.models import llama

        config = llm_config.model_config or llama.LlamaConfig.tiny()
        if llm_config.params_checkpoint:
            from ray_tpu.train.checkpoint import Checkpoint

            params = Checkpoint(llm_config.params_checkpoint).load_pytree()
        else:
            params = llama.init_params(config, jax.random.key(llm_config.seed))
        runner = ModelRunner(config, params,
                             num_blocks=llm_config.num_kv_blocks,
                             block_size=llm_config.block_size)
        self.engine = LLMEngine(runner,
                                max_batch_size=llm_config.max_batch_size,
                                tokenizer=llm_config.tokenizer)
        self.tokenizer = llm_config.tokenizer

    def __call__(self, request: Dict) -> Dict:
        return self.completions(request)

    def completions(self, request: Dict) -> Dict:
        """OpenAI-ish /v1/completions: {"prompt": str|[int], "max_tokens",
        "temperature", "top_k", "top_p", "stop_token_ids"}."""
        from ray_tpu.llm.sampling import SamplingParams

        prompt = request.get("prompt", [])
        if isinstance(prompt, str):
            if self.tokenizer is None:
                raise ValueError("string prompts require a tokenizer")
            prompt = self.tokenizer.encode(prompt)
        params = SamplingParams(
            temperature=float(request.get("temperature", 0.0)),
            top_k=int(request.get("top_k", 0)),
            top_p=float(request.get("top_p", 1.0)),
            max_tokens=int(request.get("max_tokens", 32)),
            stop_token_ids=request.get("stop_token_ids"),
            seed=request.get("seed"))
        out = self.engine.generate([prompt], params)[0]
        return {
            "id": out.request_id,
            "object": "text_completion",
            "choices": [{
                "text": out.text,
                "token_ids": out.output_token_ids,
                "finish_reason": out.finish_reason,
            }],
            "usage": {
                "prompt_tokens": len(out.prompt_token_ids),
                "completion_tokens": len(out.output_token_ids),
            },
        }


def build_llm_deployment(llm_config: LLMConfig, name: str = "llm") -> Any:
    dep = serve.deployment(LLMServer).options(
        name=name, num_replicas=llm_config.num_replicas,
        num_tpus=llm_config.num_tpus_per_replica)
    return dep.bind(llm_config)


def build_openai_app(llm_config: LLMConfig, name: str = "v1-completions"):
    """Deploys the engine and the HTTP ingress; POST /{name} serves
    completions."""
    handle = serve.run(build_llm_deployment(llm_config, name), http=True)
    return handle
