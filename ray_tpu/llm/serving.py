"""LLM serving deployment: OpenAI-style completions over the native engine.

Reference analog: python/ray/llm/_internal/serve/ (VLLMEngine wrapper
vllm_engine.py:222, vllm_deployment.py, the OpenAI router deployments/
routers/, build_openai_app). Ours wraps the native paged-attention engine
(ray_tpu.llm.engine) in a serve deployment. The engine loop runs on a
background thread inside the replica (the vLLM MQEngine pattern collapsed
in-process): request threads enqueue prompts and consume per-request token
queues, so many requests stream concurrently through one continuously-
batched engine. TP maps to a mesh inside the replica (SERVE_RULES sharding),
placed via num_tpus — the reference plans TP x PP placement groups around
vLLM (vllm_models.py:117-168).
"""

from __future__ import annotations

import dataclasses
import os
import queue
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from ray_tpu import serve


class RequestTimeoutError(TimeoutError):
    """No engine output arrived within LLMConfig.stream_timeout_s. The
    request has been aborted and its KV pages released — retrying is safe."""


class ReplicaDrainingError(RuntimeError):
    """This replica has stopped admitting (node drain / scale-down
    retirement in progress). The request was NOT submitted; route it to a
    healthy replica. The message embeds "REPLICA_DRAINING" so routers can
    classify it across actor RPC boundaries that re-wrap exception types."""

    def __init__(self, tag: str = ""):
        super().__init__(f"REPLICA_DRAINING {tag}: replica is draining; "
                         "resubmit on a healthy replica")


class SessionMigratedError(RuntimeError):
    """The replica exported this in-flight request to another replica
    (drain/scale-down migration) while a consumer was collecting it.

    mode "kv": the adoptive replica already holds the live stream — the
    consumer re-collects there with completions_collect(request_id), zero
    re-prefill. mode "replay": re-submit the same request (same
    request_id) anywhere healthy; seeded sampling reproduces the identical
    token stream from the prompt. The message embeds
    "SESSION_MIGRATED <mode> <rid>" so routers can classify the error
    across actor RPC boundaries that re-wrap exception types."""

    def __init__(self, rid: str, mode: str):
        super().__init__(f"SESSION_MIGRATED {mode} {rid}")
        self.rid = rid
        self.mode = mode


@dataclasses.dataclass
class LLMConfig:
    model_config: Any = None            # llama.LlamaConfig
    params_checkpoint: Optional[str] = None  # dir with saved params pytree
    seed: int = 0
    num_kv_blocks: int = 256
    block_size: int = 16
    max_batch_size: int = 8
    num_replicas: int = 1
    num_tpus_per_replica: float = 0.0
    tensor_parallel: int = 1            # tp axis size of the in-replica mesh
    prefill_chunk: int = 128
    tokenizer: Any = None
    # Multi-LoRA (llm/lora.py): preloaded adapters + slot-table sizing.
    lora_adapters: Any = None           # list[LoRAAdapter] | None
    max_loras: int = 8
    lora_rank: int = 8
    # Engine features (llm/engine.py): automatic prefix caching (shared
    # system prompts skip prefill) and n-gram speculative decoding
    # (greedy-only; tokens proposed from the sequence's own history).
    enable_prefix_caching: bool = True
    speculative_ngram: int = 0
    # Multi-step decode: one dispatch generates k tokens via an on-device
    # scan (engine.py) — the decode-throughput lever when dispatch latency
    # rivals per-token compute (remote-attached TPUs).
    decode_multi_step: int = 1
    # Unified ragged ticks (engine.py _mixed_tick): decode rows, spec-verify
    # rows, and prefill chunk slices share ONE kernel launch per step,
    # bucketed on total token budget. token_budget=None sizes the flat-token
    # ceiling as prefill_chunk + max_batch * (1 + speculative_ngram). The
    # split per-phase path remains for decode_multi_step > 1, prefill-only
    # replicas, and logit-feedback sampling (repetition penalty).
    unified_ticks: bool = True
    token_budget: Optional[int] = None
    # Precompile step buckets at replica start so user requests don't pay
    # XLA compiles mid-stream (vLLM-TPU startup precompile; a cold bucket
    # costs seconds of TTFT on multi-B-param models). "full" = whole
    # batch x chunk grid incl. the host-logits path (minutes of startup
    # compiles on big models, zero mid-stream stalls); "light" = the
    # sequential-traffic set (fast startup, batched-prefill shapes still
    # compile on first hit); "off" = lazy. True/False alias full/off.
    warmup_buckets: Any = "full"
    # Serving-plane knobs (llm/router.py, llm/disagg.py). routing="affinity"
    # fronts the replica fleet with the prefix-cache-affinity router
    # deployment; slo_ttft_s > 0 arms its admission gate (projected TTFT
    # above the SLO -> shed with a 429-shaped error instead of queueing
    # unboundedly); disaggregate=N runs N dedicated prefill replicas that
    # stream populated KV pages to the decode replicas over the zero-pickle
    # handoff wire (llm/disagg.py).
    routing: str = "pow2"               # "pow2" | "affinity"
    slo_ttft_s: float = 0.0
    disaggregate: int = 0
    handoff_host: str = "127.0.0.1"
    # How long completions/streams wait for the next engine output before
    # aborting the request (the abandoned-request guard).
    stream_timeout_s: float = 300.0
    # Tiered KV prefix store (llm/prefix_store.py): cold-but-reusable
    # prefix pages spill to host RAM before dropping (tier 1), and host-
    # tier victims publish into the GCS cluster prefix table (tier 2) so
    # any replica can adopt the shared working set after the owner dies,
    # drains, or the deployment restarts.
    host_prefix_mb: float = 32.0        # 0 disables the host tier
    host_prefix_low_watermark: float = 0.8
    cluster_prefix_store: bool = True   # publish/adopt via the GCS table
    # LoRA pool autoscaling (llm/lora.py LoRAPoolPolicy): grow/shrink the
    # adapter slot table off the same engine_stats() telemetry that drives
    # ReplicaPolicy.
    lora_autoscale: bool = False
    lora_min_slots: int = 1
    lora_max_slots: int = 32
    # Deployment name, stamped by build_llm_deployment — keys this fleet's
    # rows in the cluster prefix table so delete_deployment can purge them.
    deployment_name: str = ""


def _node_hex() -> Optional[str]:
    """This process's cluster node id (hex), when a core worker exists —
    the join key the router uses to map NODE_DRAINING/NODE_DEAD events to
    replicas. None outside a cluster (in-process tests, microbench)."""
    try:
        from ray_tpu.core import worker as worker_mod

        if worker_mod.is_initialized():
            nid = worker_mod.global_worker().node_id
            if isinstance(nid, (bytes, bytearray)):
                return bytes(nid).hex()
            return str(nid) if nid is not None else None
    except Exception:
        pass
    return None


def build_engine(llm_config: LLMConfig, prefill_only: bool = False):
    """Construct a ready LLMEngine per config. Shared by decode replicas
    (LLMServer) and the prefill tier (disagg.PrefillServer)."""
    import jax

    from ray_tpu.llm.engine import LLMEngine
    from ray_tpu.llm.model_runner import ModelRunner
    from ray_tpu.models import llama

    config = llm_config.model_config or llama.LlamaConfig.tiny()
    if llm_config.params_checkpoint:
        from ray_tpu.train.checkpoint import Checkpoint

        params = Checkpoint(llm_config.params_checkpoint).load_pytree()
    else:
        params = llama.init_params(config, jax.random.key(llm_config.seed))
    mesh = None
    if llm_config.tensor_parallel > 1:
        from ray_tpu.parallel.mesh import MeshConfig, build_mesh

        mesh = build_mesh(
            MeshConfig(tp=llm_config.tensor_parallel),
            devices=jax.devices()[:llm_config.tensor_parallel])
    lora_manager = None
    if llm_config.lora_adapters:
        from ray_tpu.llm.lora import LoRAManager

        lora_manager = LoRAManager(config, n_slots=llm_config.max_loras,
                                   rank=llm_config.lora_rank)
        for adapter in llm_config.lora_adapters:
            lora_manager.load_adapter(adapter)
    runner = ModelRunner(config, params,
                         num_blocks=llm_config.num_kv_blocks,
                         block_size=llm_config.block_size,
                         chunk_size=llm_config.prefill_chunk,
                         mesh=mesh, lora_manager=lora_manager)
    engine = LLMEngine(
        runner, max_batch_size=llm_config.max_batch_size,
        tokenizer=llm_config.tokenizer,
        prefill_chunk=llm_config.prefill_chunk,
        enable_prefix_caching=llm_config.enable_prefix_caching,
        speculative_ngram=llm_config.speculative_ngram,
        decode_multi_step=llm_config.decode_multi_step,
        unified_ticks=llm_config.unified_ticks,
        token_budget=llm_config.token_budget,
        prefill_only=prefill_only)
    wm = llm_config.warmup_buckets
    wm = {True: "full", False: "off"}.get(wm, wm)
    if wm not in ("off", "light", "full"):
        raise ValueError(f"warmup_buckets: {wm!r} not off/light/full")
    if wm != "off":
        engine.warmup(full=wm == "full")
    return engine


class LLMServer:
    """The replica callable: owns one engine instance + its step loop."""

    def __init__(self, llm_config: LLMConfig):
        self.engine = build_engine(llm_config)
        self.config = llm_config
        self.tokenizer = llm_config.tokenizer
        self._timeout_s = llm_config.stream_timeout_s
        self._replica_tag = f"{os.getpid()}-{uuid.uuid4().hex[:6]}"
        self._lock = threading.Lock()
        # request_id -> per-request event queue; the engine loop fans
        # RequestOutputs out to these (token-at-a-time streaming).
        self._streams: Dict[str, queue.Queue] = {}
        # Decode-throughput EWMA published via engine_stats()/gauges.
        self._tokens_per_s = 0.0
        self._tok_count = 0
        self._tok_t0 = time.monotonic()
        self._gauges = self._bind_gauges()
        # Tiered prefix store (llm/prefix_store.py): host spill tier +
        # cluster publish/adopt, each optional per config. The cluster tier
        # degrades to None outside a cluster (in-process tests, bench).
        host_tier = cluster_store = None
        if llm_config.host_prefix_mb > 0:
            from ray_tpu.llm.prefix_store import HostPrefixTier

            host_tier = HostPrefixTier(
                int(llm_config.host_prefix_mb * (1 << 20)),
                low_watermark=llm_config.host_prefix_low_watermark)
        if llm_config.cluster_prefix_store:
            from ray_tpu.llm.prefix_store import ClusterPrefixStore

            store = ClusterPrefixStore(
                llm_config.block_size, replica=self._replica_tag,
                deployment=llm_config.deployment_name)
            if store.available():
                cluster_store = store
        if host_tier is not None or cluster_store is not None:
            self.engine.attach_prefix_store(host_tier=host_tier,
                                            cluster_store=cluster_store)
        # LoRA pool autoscaling: ticked at 1 Hz from the engine loop.
        self._lora_policy = None
        if llm_config.lora_autoscale and self.engine.runner.lora is not None:
            from ray_tpu.llm.lora import (LoRAPoolPolicy,
                                          LoRAPoolPolicyConfig)

            self._lora_policy = LoRAPoolPolicy(LoRAPoolPolicyConfig(
                min_slots=llm_config.lora_min_slots,
                max_slots=llm_config.lora_max_slots))
        # KV stream listener — always on: prefill replicas stream populated
        # pages here in disaggregated mode, and draining peers migrate live
        # sessions here in every mode (llm/disagg.py wire).
        from ray_tpu.llm.disagg import KVStreamServer

        self._handoff = KVStreamServer(self._adopt_handoff,
                                       host=llm_config.handoff_host)
        # Set when this replica is being retired (node drain / scale-down):
        # new submissions bounce with ReplicaDrainingError and
        # migrate_sessions moves the live ones out.
        self._draining = False
        self._sessions_migrated_out = 0
        self._loop = threading.Thread(target=self._engine_loop, daemon=True,
                                      name=f"llm-engine-{self._replica_tag}")
        self._loop.start()

    def _bind_gauges(self):
        from ray_tpu.runtime import metric_defs as md

        tags = {"replica": self._replica_tag}
        return {
            "running": md.LLM_RUNNING.bind(tags),
            "waiting": md.LLM_WAITING.bind(tags),
            "prefilling": md.LLM_PREFILLING.bind(tags),
            "free_kv_blocks": md.LLM_KV_FREE_BLOCKS.bind(tags),
            "total_kv_blocks": md.LLM_KV_TOTAL_BLOCKS.bind(tags),
            "prefix_hits": md.LLM_PREFIX_HITS.bind(tags),
            "prefix_tokens_saved": md.LLM_PREFIX_TOKENS_SAVED.bind(tags),
            "tokens_per_s": md.LLM_TOKENS_PER_S.bind(tags),
        }

    # ---- engine loop -----------------------------------------------------

    def _engine_loop(self):
        import logging

        log = logging.getLogger(__name__)
        while True:
            try:
                with self._lock:
                    busy = self.engine.has_unfinished()
                    outs = self.engine.step() if busy else []
            except Exception as e:
                # A wedged engine must not silently strand every request:
                # surface the failure to all waiters and reset to a clean
                # scheduler state.
                log.exception("engine step failed; failing active requests")
                with self._lock:
                    import numpy as _np

                    # Drain in-flight device steps BEFORE freeing their
                    # pages (late writes into recycled pages would corrupt
                    # future sequences), then force-release everything.
                    for flight in list(self.engine._flights):
                        try:
                            _np.asarray(flight["tokens"])
                        except Exception:
                            pass
                    self.engine._flights.clear()
                    for req, blocks in self.engine._pending_release:
                        self.engine.block_manager.release_blocks(blocks)
                    self.engine._pending_release.clear()
                    for req in (list(self.engine.running)
                                + list(self.engine.prefilling)
                                + list(self.engine.waiting)):
                        req.dispatched = 0
                        self.engine.block_manager.release(req)
                    self.engine.running.clear()
                    self.engine.prefilling.clear()
                    self.engine.waiting.clear()
                for q in list(self._streams.values()):
                    q.put(e)
                continue
            for out in outs:
                self._tok_count += len(out.new_token_ids)
                q = self._streams.get(out.request_id)
                if q is not None:
                    q.put(out)
            now = time.monotonic()
            if now - self._tok_t0 >= 1.0:
                rate = self._tok_count / (now - self._tok_t0)
                self._tokens_per_s = (rate if self._tokens_per_s == 0.0
                                      else 0.7 * self._tokens_per_s
                                      + 0.3 * rate)
                self._tok_count = 0
                self._tok_t0 = now
                try:
                    self._publish_gauges()
                except Exception:
                    pass
                if self._lora_policy is not None:
                    try:
                        self._lora_pool_tick(now)
                    except Exception:
                        pass
            if not busy:
                time.sleep(0.005)

    def _submit(self, prompt, params, lora_name=None,
                request_id: Optional[str] = None) -> str:
        if self._draining:
            raise ReplicaDrainingError(self._replica_tag)
        # Honor a caller-assigned id (the router names requests): the
        # engine seeds sampling from crc32(request_id) when no explicit
        # seed is set, so a failover replay under the same id reproduces
        # the identical token stream on any replica.
        rid = request_id or uuid.uuid4().hex[:12]
        q: queue.Queue = queue.Queue()
        self._streams[rid] = q
        try:
            with self._lock:
                self.engine.add_request(prompt, params, request_id=rid,
                                        lora_name=lora_name)
        except Exception:
            self._streams.pop(rid, None)
            raise
        return rid

    def _parse(self, request: Dict):
        from ray_tpu.llm.sampling import SamplingParams

        prompt = request.get("prompt", [])
        if isinstance(prompt, str):
            if self.tokenizer is None:
                raise ValueError("string prompts require a tokenizer")
            prompt = self.tokenizer.encode(prompt)
        params = SamplingParams(
            temperature=float(request.get("temperature", 0.0)),
            top_k=int(request.get("top_k", 0)),
            top_p=float(request.get("top_p", 1.0)),
            max_tokens=int(request.get("max_tokens", 32)),
            stop_token_ids=request.get("stop_token_ids"),
            seed=request.get("seed"))
        return prompt, params, request.get("lora_name"), \
            request.get("request_id")

    def _abort(self, rid: str) -> bool:
        """Stop decoding for a dead consumer and free its KV pages."""
        with self._lock:
            aborted = self.engine.abort_request(rid)
        self._streams.pop(rid, None)
        return aborted

    def abort(self, rid: str) -> bool:
        """Router-facing abort: after a collect call fails mid-generation
        the router replays the request elsewhere and aborts the orphan here
        so it stops burning decode compute and KV pages. Idempotent."""
        return self._abort(rid)

    # ---- stats / observability ------------------------------------------

    def engine_stats(self) -> Dict:
        """Per-replica load signal the router's pow2/admission logic
        consumes; also pushes the same numbers to the bound gauges."""
        with self._lock:
            s = self.engine.stats()
        s["tokens_per_s"] = round(self._tokens_per_s, 1)
        s["replica"] = self._replica_tag
        s["draining"] = self._draining
        s["node_id"] = _node_hex()
        s["sessions_migrated_out"] = self._sessions_migrated_out
        if self._handoff is not None:
            s["handoff_address"] = list(self._handoff.address)
            s["handoffs_adopted"] = self._handoff.handoffs_adopted
            s["handoffs_rejected"] = self._handoff.handoffs_rejected
        try:
            self._publish_gauges(s)
        except Exception:
            pass
        return s

    def flight_records(self, limit: Optional[int] = None,
                       request_id: Optional[str] = None) -> List[Dict]:
        """Engine tick flight recorder (llm/engine.py): the per-tick batch
        composition / budget / recompile ring, for attributing a slow token
        to its cause. `request_id` filters to ticks that emitted for it."""
        with self._lock:
            return self.engine.tick_records(limit=limit,
                                            request_id=request_id)

    def _publish_gauges(self, s: Optional[Dict] = None):
        if s is None:
            with self._lock:
                s = self.engine.stats()
            s["tokens_per_s"] = round(self._tokens_per_s, 1)
        g = self._gauges
        g["running"].set(s["running"])
        g["waiting"].set(s["waiting"])
        g["prefilling"].set(s["prefilling"])
        g["free_kv_blocks"].set(s["free_kv_blocks"])
        g["total_kv_blocks"].set(s["total_kv_blocks"])
        g["prefix_hits"].set(s["prefix_hits"])
        g["prefix_tokens_saved"].set(s["prefix_tokens_saved"])
        g["tokens_per_s"].set(s["tokens_per_s"])

    # ---- KV handoff + live migration (llm/disagg.py wire) ----------------

    def handoff_address(self) -> List:
        return list(self._handoff.address)

    def resume_admission(self) -> None:
        """Cancel a drain: start admitting again. For the reprieve path —
        the node's drain was withdrawn, or a scale-down decision reversed
        before the replica was retired. Sessions already migrated out
        stay migrated (their KV lives on the adoptive replica now)."""
        self._draining = False

    def migrate_sessions(self, target_address, *,
                         timeout: float = 60.0) -> Dict:
        """Drain-plane live migration: stop admitting, then move every live
        request to `target_address` (another replica's KV stream listener).

        Decoding requests travel with their populated KV pages over the
        zero-pickle raw-frame wire — whole-stream-or-discard, so a target
        dying mid-adopt leaves nothing torn and the request falls back to
        seeded replay from the prompt. Requests still queued or mid-prefill
        always take the replay path (their partial KV is discarded whole).
        Requests that finish while the async pipeline drains complete
        normally — migration never double-delivers. Consumers blocked in
        completions/_collect get a SessionMigratedError naming the mode so
        the router re-collects (kv) or re-submits (replay); no client ever
        observes this replica going away. Returns per-mode rid lists."""
        from ray_tpu.llm.disagg import migrate_session

        self._draining = True
        migrated: List[str] = []
        replayed: List[str] = []
        finished: List[str] = []
        exports: List[tuple] = []
        with self._lock:
            # Harvest in-flight device steps first: their tokens commit,
            # some requests finish here (the migration-vs-completion race
            # resolves to exactly-once delivery), and afterwards no device
            # write can land in any exported page.
            for out in self.engine.drain_flights():
                q = self._streams.get(out.request_id)
                if q is not None:
                    q.put(out)
                if out.finished:
                    finished.append(out.request_id)
            live = ([r.id for r in self.engine.running]
                    + [r.id for r in self.engine.prefilling]
                    + [r.id for r in self.engine.waiting])
            for rid in live:
                state, mode = self.engine.export_session(rid)
                if state is None:
                    continue
                if mode == "kv":
                    blocks = state.pop("blocks")
                    k, v = self.engine.runner.gather_pages(blocks)
                    self.engine.block_manager.release_blocks(blocks)
                    exports.append((rid, state, k, v))
                else:
                    replayed.append(rid)
        # Stream outside the lock (PrefillServer's discipline: socket time
        # must never serialize engine work — and the failure path below
        # must not hold the engine hostage either).
        send_failed: List[str] = []
        for rid, state, k, v in exports:
            # The pause is a first-class trace span, not a silent gap: it
            # starts at export (the engine stamped t_handoff then — decode
            # stopped for this request the moment it left the scheduler)
            # and ends when the target acked adoption. The adopter books
            # the same interval into the request's stall_s via t_handoff.
            t_pause0 = (state.get("timing") or {}).get("t_handoff",
                                                       time.time())
            from ray_tpu.util import tracing

            try:
                # The stream rides under the request's trace context so the
                # kv_handoff span (opened inside send_handoff) — and the
                # adopter's kv_adopt span parent-linked to it over the wire
                # — stitch into this request's trace, not a fresh one.
                with tracing.trace_context(tracing.request_trace_id(rid),
                                           None):
                    migrate_session(target_address, state, k, v,
                                    timeout=timeout)
                    migrated.append(rid)
                    tracing.record_span(
                        "llm:migration_pause", "llm", t_pause0, time.time(),
                        request_id=rid, source=self._replica_tag, mode="kv")
                self.engine.flight_records.append({
                    "t": t_pause0, "kind": "migration_pause",
                    "dur_ms": round((time.time() - t_pause0) * 1e3, 3),
                    "emitted": {}, "request_id": rid})
            except Exception:
                # Atomic wire: nothing half-adopted — but a timeout with a
                # LOST ACK can leave the session fully adopted (decoding
                # with no consumer) on the target while we replay it from
                # the prompt. Report these rids so the router best-effort
                # aborts them on the target before the replay starts.
                send_failed.append(rid)
                replayed.append(rid)
        for rid in migrated:
            q = self._streams.get(rid)
            if q is not None:
                q.put(SessionMigratedError(rid, "kv"))
        for rid in replayed:
            q = self._streams.get(rid)
            if q is not None:
                q.put(SessionMigratedError(rid, "replay"))
        self._sessions_migrated_out += len(migrated)
        return {"migrated": migrated, "replayed": replayed,
                "send_failed": send_failed, "finished": finished,
                "replica": self._replica_tag}

    def push_prefixes(self, target_address, *, limit: int = 16,
                      timeout: float = 60.0) -> Dict:
        """Drain-plane working-set handoff: stream the hottest reusable
        prefix pages (device `reusable` pool first, then the host tier) to
        `target_address` — another replica's KV stream listener — so a
        drain's successor starts warm instead of re-prefilling the shared
        prompts. Same whole-or-nothing raw-frame wire as
        migrate_sessions; a failed send costs nothing (the pages were
        already spill candidates)."""
        from ray_tpu.llm.disagg import send_handoff

        with self._lock:
            export = self.engine.export_prefixes(limit=limit)
        if export is None:
            return {"pushed": 0, "replica": self._replica_tag}
        state, k, v = export
        try:
            send_handoff(target_address, state, k, v, timeout=timeout)
        except Exception:
            return {"pushed": 0, "replica": self._replica_tag,
                    "error": "send_failed"}
        return {"pushed": len(state["entries"]),
                "replica": self._replica_tag}

    def _lora_pool_tick(self, now: float) -> None:
        """1 Hz LoRA pool autoscale: LoRAPoolPolicy reads the engine stats
        and, when the watermarks say so, resizes the adapter slot table
        under the engine lock (the resize rebuilds the stacked tensors, so
        it must not race a step)."""
        mgr = self.engine.runner.lora
        with self._lock:
            target = self._lora_policy.desired(self.engine.stats(),
                                               now)
            if target is not None and target != mgr.n_slots - 1:
                mgr.resize(target)

    def _adopt_handoff(self, state: Dict, k_pages, v_pages) -> bool:
        # Drain-plane prefix push (push_prefixes): cached pages, not a
        # live session — adopt straight into the prefix cache; no stream
        # queue, no consumer.
        if state.get("prefix"):
            with self._lock:
                return self.engine.adopt_prefix(state, k_pages,
                                                v_pages) > 0
        # The stream queue must exist BEFORE the request can start decoding
        # (the engine loop drops outputs with no queue), and the ack goes
        # back only after adopt_request returns — so by the time the router
        # calls completions_collect, both are in place.
        rid = state["id"]
        q: queue.Queue = queue.Queue()
        self._streams[rid] = q
        with self._lock:
            ok = self.engine.adopt_request(state, k_pages, v_pages)
        if not ok:
            self._streams.pop(rid, None)
        return ok

    # ---- LoRA management (multiplex) ------------------------------------

    def load_lora_adapter(self, adapter) -> Dict:
        """Dynamically install a LoRAAdapter (LRU-evicting when full)."""
        if self.engine.runner.lora is None:
            raise ValueError("replica built without LoRA support "
                             "(set LLMConfig.lora_adapters)")
        with self._lock:
            slot = self.engine.runner.lora.load_adapter(adapter)
        return {"name": adapter.name, "slot": slot}

    def list_lora_adapters(self) -> Dict:
        mgr = self.engine.runner.lora
        return {"adapters": mgr.loaded if mgr is not None else []}

    # ---- API -------------------------------------------------------------

    def __call__(self, request: Dict) -> Dict:
        return self.completions(request)

    def completions(self, request: Dict) -> Dict:
        """OpenAI-ish /v1/completions: {"prompt": str|[int], "max_tokens",
        "temperature", "top_k", "top_p", "stop_token_ids"}."""
        prompt, params, lora_name, rid = self._parse(request)
        rid = self._submit(prompt, params, lora_name, rid)
        return self._collect(rid)

    def completions_collect(self, request_id: str) -> Dict:
        """Wait out an already-submitted request (the router calls this on
        the decode replica after a prefill handoff was adopted)."""
        if request_id not in self._streams:
            raise KeyError(f"unknown request {request_id!r} "
                           "(handoff not adopted here?)")
        return self._collect(request_id)

    def _collect(self, rid: str) -> Dict:
        from ray_tpu.llm.disagg import _completion_response

        q = self._streams[rid]
        try:
            while True:
                try:
                    out = q.get(timeout=self._timeout_s)
                except queue.Empty:
                    # Consumer still here but the engine went silent, or the
                    # client's deadline passed: stop burning KV blocks.
                    self._abort(rid)
                    raise RequestTimeoutError(
                        f"request {rid}: no engine output within "
                        f"{self._timeout_s}s; request aborted") from None
                if isinstance(out, Exception):
                    raise out
                if out.finished:
                    break
        finally:
            self._streams.pop(rid, None)
        return _completion_response(out)

    def completions_stream(self, request: Dict):
        """Streaming completions: a generator of OpenAI-style chunk events,
        one per sampled token. Consume through
        handle.options("completions_stream").remote_stream(request)."""
        prompt, params, lora_name, rid = self._parse(request)
        rid = self._submit(prompt, params, lora_name, rid)
        q = self._streams[rid]
        finished = False
        try:
            while True:
                try:
                    out = q.get(timeout=self._timeout_s)
                except queue.Empty:
                    raise RequestTimeoutError(
                        f"request {rid}: no engine output within "
                        f"{self._timeout_s}s; request aborted") from None
                if isinstance(out, Exception):
                    raise out
                for t in out.new_token_ids:
                    yield {"id": rid, "object": "text_completion.chunk",
                           "token": int(t), "finished": False}
                if out.finished:
                    finished = True
                    yield {"id": rid, "object": "text_completion.chunk",
                           "token": None, "finished": True,
                           "finish_reason": out.finish_reason,
                           "text": out.text,
                           "token_ids": out.output_token_ids}
                    return
        finally:
            # Runs on timeout, engine error, AND consumer disappearance
            # (GeneratorExit via _StreamingResponse.__del__): an unfinished
            # request must not keep decoding to max_tokens for a dead
            # stream — abort it and free its pages.
            if not finished:
                self._abort(rid)
            self._streams.pop(rid, None)


def build_llm_deployment(llm_config: LLMConfig, name: str = "llm") -> Any:
    if llm_config.deployment_name != name:
        llm_config = dataclasses.replace(llm_config, deployment_name=name)
    dep = serve.deployment(LLMServer).options(
        name=name, num_replicas=llm_config.num_replicas,
        num_tpus=llm_config.num_tpus_per_replica,
        max_ongoing_requests=llm_config.max_batch_size)
    return dep.bind(llm_config)


def build_routed_app(llm_config: LLMConfig, name: str = "v1-completions",
                     *, http: bool = True):
    """Deploys the full serving plane: `{name}-engine` (decode or colocated
    replicas), optionally `{name}-prefill` (disaggregate > 0), and the
    `{name}` router deployment fronting them. Returns the router handle."""
    from ray_tpu.llm.disagg import PrefillServer
    from ray_tpu.llm.router import LLMRouter

    tiers = [build_llm_deployment(llm_config, f"{name}-engine")]
    prefill_name = None
    if llm_config.disaggregate > 0:
        prefill_name = f"{name}-prefill"
        tiers.append(serve.deployment(PrefillServer).options(
            name=prefill_name, num_replicas=llm_config.disaggregate,
            num_tpus=llm_config.num_tpus_per_replica,
            max_ongoing_requests=llm_config.max_batch_size,
        ).bind(llm_config))
    # Tiers first: the router resolves their replica handles lazily on the
    # first request, and they must already be deployed by then.
    serve.run(tiers)
    router = serve.deployment(LLMRouter).options(
        name=name, num_replicas=1,
        max_ongoing_requests=8 * llm_config.max_batch_size,
    ).bind(llm_config, f"{name}-engine", prefill_name)
    return serve.run(router, http=http)


def build_openai_app(llm_config: LLMConfig, name: str = "v1-completions"):
    """Deploys the engine and the HTTP ingress; POST /{name} serves
    completions. With routing="affinity" or disaggregate > 0 the app gets
    the router front (build_routed_app) instead of a bare replica fleet."""
    if llm_config.routing == "affinity" or llm_config.disaggregate > 0:
        return build_routed_app(llm_config, name)
    handle = serve.run(build_llm_deployment(llm_config, name), http=True)
    return handle
