"""OpenAI-compatible API router over the native LLM engine.

Reference analog: python/ray/llm/_internal/serve/deployments/routers/
(the OpenAI-compatible ingress deployment in front of the vLLM engine
deployment) and build_openai_app. Ours is a serve deployment that COMPOSES
with the engine deployment through a DeploymentHandle (deployment-calling-
deployment — the reference's router→LLMDeployment graph), adding:

  * /v1/chat/completions semantics: a chat template renders messages to
    prompt tokens; usage accounting and OpenAI-shaped responses.
  * /v1/completions passthrough.
  * /v1/models listing (one entry per registered model / LoRA adapter id).
  * `model` routing: requests name a model id; multiplexed adapters map to
    the engine's LoRA slots (llm/lora.py), unknown ids get a 404-shaped
    error dict.

The router is stateless (templates + handle cache only), so it scales with
num_replicas independently of engine replicas.
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu import serve


class ChatTemplate:
    """Minimal llama-3-style chat template over a token-level tokenizer.

    render(messages) -> token ids. The tokenizer needs encode(str)->[int];
    special role markers are encoded inline (a real deployment would use
    reserved special tokens; token-level fidelity is not required for
    routing correctness, which is what this layer owns)."""

    def __init__(self, tokenizer, *, system_default: Optional[str] = None):
        self.tokenizer = tokenizer
        self.system_default = system_default

    def render(self, messages: List[Dict[str, str]]) -> List[int]:
        parts = []
        if self.system_default and not any(
                m.get("role") == "system" for m in messages):
            parts.append(("system", self.system_default))
        for m in messages:
            parts.append((m.get("role", "user"), m.get("content", "")))
        text = ""
        for role, content in parts:
            text += f"<|{role}|>\n{content}\n<|end|>\n"
        text += "<|assistant|>\n"
        return self.tokenizer.encode(text)


class OpenAIRouter:
    """The router replica. Init args: engine deployment name -> model id map
    and an optional tokenizer/template for chat rendering."""

    def __init__(self, models: Dict[str, str], tokenizer=None,
                 chat_template: Optional[ChatTemplate] = None):
        """models: model_id -> engine deployment name. Adapter ids use
        "base_id:adapter_name" and route to the base engine with
        lora_name=adapter_name."""
        self.models = dict(models)
        self.tokenizer = tokenizer
        self.template = chat_template or (
            ChatTemplate(tokenizer) if tokenizer is not None else None)
        self._handles: Dict[str, Any] = {}
        self.created = int(time.time())

    def _resolve(self, model_id: Optional[str]):
        """-> (engine handle, lora_name | None) or an error dict."""
        if model_id is None and len(self.models) == 1:
            model_id = next(iter(self.models))
        lora = None
        target = self.models.get(model_id)
        if target is None and model_id and ":" in model_id:
            base, lora = model_id.split(":", 1)
            target = self.models.get(base)
        if target is None:
            return None, None, {
                "error": {"message": f"model {model_id!r} not found",
                          "type": "invalid_request_error", "code": 404}}
        if target not in self._handles:
            self._handles[target] = serve.get_deployment_handle(target)
        return self._handles[target], lora, None

    # ---- endpoints -------------------------------------------------------

    def models_list(self, _request=None) -> Dict:
        """GET /v1/models."""
        return {"object": "list", "data": [
            {"id": mid, "object": "model", "created": self.created,
             "owned_by": "ray_tpu"} for mid in sorted(self.models)]}

    def completions(self, request: Dict) -> Dict:
        handle, lora, err = self._resolve(request.get("model"))
        if err:
            return err
        req = dict(request)
        if lora:
            req["lora_name"] = lora
        out = handle.options("completions").remote(req).result(timeout=600)
        out["model"] = request.get("model")
        return out

    def chat_completions(self, request: Dict) -> Dict:
        """POST /v1/chat/completions: renders messages through the chat
        template, generates, wraps in chat shape."""
        handle, lora, err = self._resolve(request.get("model"))
        if err:
            return err
        messages = request.get("messages", [])
        if self.template is None:
            return {"error": {"message": "no chat template configured",
                              "type": "invalid_request_error", "code": 400}}
        prompt = self.template.render(messages)
        req = {k: v for k, v in request.items()
               if k in ("max_tokens", "temperature", "top_k", "top_p",
                        "stop_token_ids", "seed")}
        req["prompt"] = prompt
        if lora:
            req["lora_name"] = lora
        out = handle.options("completions").remote(req).result(timeout=600)
        if "error" in out:
            return out
        choice = out["choices"][0]
        text = choice.get("text")
        if text is None and self.tokenizer is not None:
            try:
                text = self.tokenizer.decode(choice.get("token_ids", []))
            except Exception:
                text = None
        return {
            "id": "chatcmpl-" + uuid.uuid4().hex[:12],
            "object": "chat.completion",
            "created": int(time.time()),
            "model": request.get("model"),
            "choices": [{
                "index": 0,
                "message": {"role": "assistant", "content": text,
                            "token_ids": choice.get("token_ids")},
                "finish_reason": choice.get("finish_reason"),
            }],
            "usage": out.get("usage", {}),
        }

    def chat_completions_stream(self, request: Dict):
        """Streaming chat: a generator of chat.completion.chunk events
        (consumed via handle.options(...).remote_stream or the HTTP proxy's
        SSE path)."""
        handle, lora, err = self._resolve(request.get("model"))
        if err:
            yield err
            return
        if self.template is None:
            yield {"error": {"message": "no chat template configured",
                             "type": "invalid_request_error", "code": 400}}
            return
        prompt = self.template.render(request.get("messages", []))
        req = {k: v for k, v in request.items()
               if k in ("max_tokens", "temperature", "top_k", "top_p",
                        "stop_token_ids", "seed")}
        req["prompt"] = prompt
        if lora:
            req["lora_name"] = lora
        cid = "chatcmpl-" + uuid.uuid4().hex[:12]
        for ref in handle.options("completions_stream").remote_stream(req):
            chunk = ray_tpu.get(ref, timeout=600)
            if chunk.get("finished"):
                text = chunk.get("text")
                if text is None and self.tokenizer is not None:
                    try:
                        text = self.tokenizer.decode(
                            chunk.get("token_ids", []))
                    except Exception:
                        text = None
                yield {"id": cid, "object": "chat.completion.chunk",
                       "choices": [{"index": 0, "delta": {},
                                    "finish_reason":
                                        chunk.get("finish_reason")}],
                       "text": text}
                return
            delta: Dict[str, Any] = {"token": chunk.get("token")}
            if self.tokenizer is not None and chunk.get("token") is not None:
                try:
                    delta["content"] = self.tokenizer.decode(
                        [chunk["token"]])
                except Exception:
                    pass
            yield {"id": cid, "object": "chat.completion.chunk",
                   "choices": [{"index": 0, "delta": delta,
                                "finish_reason": None}]}

    def __call__(self, request: Dict) -> Dict:
        """Default POST target: dispatch on an `endpoint` field (the HTTP
        proxy posts the parsed JSON body)."""
        endpoint = (request or {}).get("endpoint", "chat/completions")
        if endpoint == "models":
            return self.models_list()
        if endpoint == "completions":
            return self.completions(request)
        return self.chat_completions(request)


def build_router_app(models: Dict[str, str], *, tokenizer=None,
                     name: str = "openai", num_replicas: int = 1):
    """Deploy an OpenAIRouter in front of already-deployed engine
    deployments. Returns the router handle."""
    dep = serve.deployment(OpenAIRouter).options(
        name=name, num_replicas=num_replicas)
    return serve.run(dep.bind(models, tokenizer))
