"""Two-tier cluster-wide KV prefix store.

The BlockManager's prefix cache (llm/engine.py) is a per-replica LRU: when
allocation pressure recycles a parked `reusable` block, its KV is gone, and
when the replica dies the whole shared working set dies with it — every
survivor re-prefills the same system prompt from scratch. This module adds
the two tiers that make cold prefix pages outlive both events:

  * Tier 1 — HostPrefixTier: a byte-capacity LRU of evicted prefix blocks
    in host RAM. BlockManager's eviction path (the `reusable.popitem` in
    `_take_free_block`) hands the victim block here before dropping it;
    admission (`LLMEngine._admit`) promotes matching blocks straight back
    into fresh device pages instead of re-prefilling them.

  * Tier 2 — ClusterPrefixStore: host-tier victims are demoted over the
    zero-pickle raw-frame RPC wire (rpc.py call_raw) into a GCS-resident
    prefix table modeled on the checkpoint shard-relocation registry. The
    pages are homed in the GCS byte plane ON PURPOSE: objects a replica
    `put()`s ride the owner-addressed ownership protocol (core/worker.py)
    and are reaped by delete-on-zero when their owner dies — exactly the
    event this store must survive. ANY replica can adopt a spilled prefix;
    the working set survives replica death, drain-based scale-down, and
    serving-fleet restarts.

Addressing: cluster entries are keyed by `prefix_digest_chain` under a
FIXED salt (`CLUSTER_PREFIX_SALT`) seeded with the adapter name, because
cluster addresses must be comparable across processes — the opposite of
the engine's deliberately per-process salt. The anti-forgery property the
random salt bought moves to adoption time: every entry carries its full
root-anchored token prefix, and an adopter scatters pages only after
verifying those tokens byte-for-byte against its own prompt (plus a
weights_version equality check, so KV spilled under old weights is never
decoded against new ones).

Wire format of a spilled payload (one bytes buffer, identical frame layout
to the disagg/migration socket stream so the codec is shared muscle):

    [u64 body len][u8 kind=2][JSON meta: kv dtype/shape]
    [u64][u8 kind=1][97B _AMETA][raw k-page bytes]   x ceil(bytes/1MiB)
    [u64][u8 kind=1][97B _AMETA][raw v-page bytes]   x ceil(bytes/1MiB)

Frames are self-delimiting, so a lookup reply carrying N blocks is simply
N buffers concatenated. Decoding is whole-or-nothing: a truncated buffer
raises TruncatedSpillError and the adopter registers NOTHING (the same
ack-after-adoption discipline as session migration).

This module is on graftlint's hot-pickle frozen path set: no pickle,
either direction, ever — counter-proven by tests/test_prefix_store.py.
"""

from __future__ import annotations

import json
import logging
import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ray_tpu.collective.cpu_group import (
    _AMETA, _HDR, _K_ARRAY, _chunks, _frame_views)
from ray_tpu.core import serialization as _ser

logger = logging.getLogger(__name__)

# JSON control frame kind — shared with llm/disagg.py's handoff wire.
_K_JSON = 2
_CHUNK_BYTES = 1 << 20

# Fixed cross-process salt for cluster prefix addresses (the engine's
# per-process _PREFIX_CACHE_SALT deliberately prevents cross-replica digest
# comparison; the cluster table requires it). blake2b keyed hashing caps
# keys at 64 bytes.
CLUSTER_PREFIX_SALT = b"ray-tpu/cluster-prefix-store/v1"


def cluster_chain(tokens: Sequence[int], block_size: int,
                  lora_id: str = "") -> List[bytes]:
    """Cluster-comparable digest chain for `tokens`, seeded by adapter name
    (LoRA changes wk/wv, so KV content differs per adapter and entries are
    keyed per `lora_id`)."""
    from ray_tpu.llm.engine import prefix_digest_chain

    return prefix_digest_chain(tokens, block_size, salt=CLUSTER_PREFIX_SALT,
                               seed=(lora_id or "").encode())


class TruncatedSpillError(RuntimeError):
    """A spilled payload buffer ended mid-frame: discard it whole."""


# --------------------------------------------------------------- page codec


def encode_pages(meta: dict, k_pages, v_pages) -> bytes:
    """Serialize (meta, k, v) into one raw-frame buffer (format above).
    kv dtype/shape ride in the JSON frame; array frames carry raw bytes."""
    k = np.ascontiguousarray(k_pages)
    v = np.ascontiguousarray(v_pages)
    meta = dict(meta)
    meta["kv_dtype"] = str(k.dtype)
    meta["kv_shape"] = list(k.shape)
    body = json.dumps(meta).encode()
    parts: List[bytes] = [_HDR.pack(len(body), _K_JSON), body]
    for arr in (k, v):
        flat = arr.reshape(-1).view(np.uint8)
        for off, n in _chunks(0, flat.size, _CHUNK_BYTES):
            for view in _frame_views(flat[off:off + n], flat.shape, off):
                parts.append(bytes(view))
    return b"".join(parts)


class _BufReader:
    """Sequential frame reader over a spilled-payload buffer. Mirrors
    disagg._recv_frame's semantics — including the deserialize_fast counter
    bumps the zero-pickle counter-proof keys on — with truncation raising
    instead of blocking."""

    __slots__ = ("view", "pos")

    def __init__(self, buf):
        self.view = memoryview(buf).cast("B")
        self.pos = 0

    def eof(self) -> bool:
        return self.pos >= self.view.nbytes

    def _take(self, n: int) -> memoryview:
        if self.pos + n > self.view.nbytes:
            raise TruncatedSpillError(
                f"spill buffer truncated at byte {self.pos} "
                f"(need {n} more of {self.view.nbytes})")
        out = self.view[self.pos:self.pos + n]
        self.pos += n
        return out

    def read_frame(self) -> Tuple[str, Any]:
        length, kind = _HDR.unpack(self._take(_HDR.size))
        if kind == _K_JSON:
            return "json", json.loads(bytes(self._take(length)).decode())
        if kind != _K_ARRAY:
            raise TruncatedSpillError(
                f"spill protocol error: unknown frame kind {kind}")
        fields = _AMETA.unpack(self._take(_AMETA.size))
        dtype = np.dtype(fields[0].rstrip(b"\x00").decode())
        ndim = fields[1]
        shape = tuple(fields[2:2 + ndim])
        offset, nelems = fields[10], fields[11]
        out = np.empty(shape, dtype)
        flat = out.reshape(-1)
        total, got = flat.size, 0
        while True:
            if nelems:
                chunk = self._take(length - _AMETA.size)
                memoryview(flat[offset:offset + nelems]).cast("B")[:] = chunk
                got += nelems
            _ser.counters["deserialize_fast"] += 1
            if got >= total:
                return "array", flat
            length, kind = _HDR.unpack(self._take(_HDR.size))
            if kind != _K_ARRAY:
                raise TruncatedSpillError(
                    "spill protocol error: truncated array stream")
            fields = _AMETA.unpack(self._take(_AMETA.size))
            offset, nelems = fields[10], fields[11]


def decode_pages(reader) -> Tuple[dict, np.ndarray, np.ndarray]:
    """Decode one (meta, k, v) triple off a _BufReader (or a buffer)."""
    r = reader if isinstance(reader, _BufReader) else _BufReader(reader)
    kind, meta = r.read_frame()
    if kind != "json":
        raise TruncatedSpillError("spill buffer missing its meta frame")
    kind_k, kflat = r.read_frame()
    kind_v, vflat = r.read_frame()
    if kind_k != "array" or kind_v != "array":
        raise TruncatedSpillError("spill buffer missing a page array")
    dtype = np.dtype(meta.pop("kv_dtype"))
    shape = tuple(meta.pop("kv_shape"))
    k = kflat.view(dtype).reshape(shape)
    v = vflat.view(dtype).reshape(shape)
    return meta, k, v


def decode_all(buf) -> List[Tuple[dict, np.ndarray, np.ndarray]]:
    """Decode every concatenated (meta, k, v) triple in `buf` — the shape
    of a multi-block lookup reply. Whole-or-nothing: any truncation raises
    and the caller adopts none of it."""
    r = _BufReader(buf)
    out = []
    while not r.eof():
        out.append(decode_pages(r))
    return out


# ------------------------------------------------------------------- tier 1


class HostPrefixTier:
    """Byte-capacity LRU of evicted prefix blocks in host RAM.

    Entries are per-block: {digest, tokens (root-anchored, through this
    block), k, v, lora_slot, lora_name, weights_version, nbytes}. Crossing
    the high watermark demotes LRU victims through `on_demote` (wired to
    ClusterPrefixStore.publish) down to the low watermark — promotion back
    to the device happens in LLMEngine._admit via get()."""

    def __init__(self, capacity_bytes: int, *,
                 high_watermark: float = 1.0, low_watermark: float = 0.8,
                 on_demote: Optional[Callable[[dict], None]] = None):
        self.capacity_bytes = int(capacity_bytes)
        self.high = float(high_watermark)
        self.low = float(low_watermark)
        self.on_demote = on_demote
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        self.spills = 0
        self.demotions = 0
        self._entries: "OrderedDict[bytes, dict]" = OrderedDict()
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self._entries)

    def put(self, digest: bytes, entry: dict) -> None:
        entry = dict(entry)
        entry["digest"] = digest
        nbytes = int(entry["nbytes"])
        if nbytes > self.capacity_bytes:
            return  # one block larger than the whole tier: never fits
        demoted: List[dict] = []
        with self._lock:
            old = self._entries.pop(digest, None)
            if old is not None:
                self.bytes -= int(old["nbytes"])
            self._entries[digest] = entry
            self.bytes += nbytes
            self.spills += 1
            if self.bytes > self.high * self.capacity_bytes:
                floor = self.low * self.capacity_bytes
                while self._entries and self.bytes > floor:
                    _, victim = self._entries.popitem(last=False)
                    self.bytes -= int(victim["nbytes"])
                    self.demotions += 1
                    demoted.append(victim)
        self._metric("host")
        for victim in demoted:
            if self.on_demote is not None:
                try:
                    self.on_demote(victim)
                except Exception:
                    logger.exception("prefix demotion to cluster store failed")
        self._gauge()

    def get(self, digest: bytes) -> Optional[dict]:
        with self._lock:
            e = self._entries.get(digest)
            if e is None:
                self.misses += 1
                return None
            self._entries.move_to_end(digest)
            self.hits += 1
            return e

    def clear(self) -> int:
        """Drop everything (weight hot-swap: cached KV is stale)."""
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self.bytes = 0
        self._gauge()
        return n

    def hottest(self, limit: int) -> List[dict]:
        """Most-recently-touched entries first (drain-time push set)."""
        with self._lock:
            return [self._entries[k]
                    for k in list(reversed(self._entries))[:limit]]

    def stats(self) -> Dict[str, int]:
        return {"entries": len(self._entries), "bytes": self.bytes,
                "hits": self.hits, "misses": self.misses,
                "spills": self.spills, "demotions": self.demotions}

    @staticmethod
    def _metric(tier: str):
        try:
            from ray_tpu.runtime import metric_defs

            metric_defs.LLM_PREFIX_SPILLS.inc(tags={"tier": tier})
        except Exception:
            pass

    def _gauge(self):
        try:
            from ray_tpu.runtime import metric_defs

            metric_defs.LLM_PREFIX_STORE_BYTES.set(self.bytes)
        except Exception:
            pass


# ------------------------------------------------------------------- tier 2


class ClusterPrefixStore:
    """Client for the GCS prefix table (gcs/server.py handle_prefix_*).

    All traffic rides `call_raw` — schema'd wire.Prefix*Msg headers plus
    raw-frame page payloads, zero pickle in either direction. Every method
    is best-effort: a missing worker, an old GCS ("no handler"), or a
    timeout degrades to a cache miss, never an engine error. `transport`
    is injectable for tests (a direct bridge onto a GcsServer instance)."""

    def __init__(self, block_size: int, *, replica: str = "",
                 deployment: str = "", timeout_s: float = 5.0,
                 transport: Optional[Callable] = None):
        self.block_size = int(block_size)
        self.replica = replica
        self.deployment = deployment
        self.timeout_s = float(timeout_s)
        self._transport = transport
        self.published = 0
        self.adopted_blocks = 0
        self.stale_rejected = 0
        self.errors = 0

    # -- transport ---------------------------------------------------------

    def _call(self, method: str, m: bytes, payload=b"",
              wait: bool = True) -> Optional[Tuple[bytes, Any]]:
        """One raw-frame RPC to the GCS. wait=False fires and forgets (the
        demotion path must never stall the engine's scheduling tick on a
        head-node round trip)."""
        if self._transport is not None:
            return self._transport(method, m, payload)
        try:
            from ray_tpu.core.worker import global_worker

            core = global_worker()
        except Exception:
            return None
        try:
            coro = core.gcs.call_raw(method, m=m, payload=payload,
                                     timeout=self.timeout_s)
            if not wait:
                core.io.spawn(coro)
                return None
            return core.io.run(coro, timeout=self.timeout_s + 2)
        except Exception:
            self.errors += 1
            return None

    def available(self) -> bool:
        if self._transport is not None:
            return True
        try:
            from ray_tpu.core.worker import global_worker

            return global_worker() is not None
        except Exception:
            return False

    def _node_id(self) -> bytes:
        try:
            from ray_tpu.core.worker import global_worker

            return bytes(global_worker().node_id)
        except Exception:
            return b""

    # -- operations ----------------------------------------------------------

    def publish(self, entry: dict, *, wait: bool = False) -> bool:
        """Demote one host-tier victim into the cluster table. The payload
        buffer is complete before the RPC leaves — an upsert either lands
        whole or not at all (partial spills cannot exist server-side)."""
        from ray_tpu.runtime import wire

        tokens = list(entry["tokens"])
        lora_id = entry.get("lora_name") or ""
        if not tokens or len(tokens) % self.block_size:
            return False
        digest = cluster_chain(tokens, self.block_size, lora_id)[-1]
        payload = encode_pages({}, entry["k"], entry["v"])
        m = wire.PrefixEntryMsg(
            digest=digest, lora_id=lora_id,
            weights_version=int(entry.get("weights_version", 0)),
            block_size=self.block_size, n_tokens=len(tokens),
            token_ids=[int(t) for t in tokens], nbytes=len(payload),
            owner_replica=self.replica, node_id=self._node_id(),
            deployment=self.deployment).encode()
        import time

        from ray_tpu.util import tracing

        t0 = time.time()
        out = self._call("prefix_upsert", m, payload, wait=wait)
        tracing.record_span("llm:prefix_spill", "llm", t0, time.time(),
                            tokens=len(tokens), bytes=len(payload),
                            replica=self.replica)
        self.published += 1
        try:
            from ray_tpu.runtime import events, metric_defs

            metric_defs.LLM_PREFIX_SPILLS.inc(tags={"tier": "store"})
            events.emit(events.LLM_PREFIX_SPILLED,
                        f"prefix spilled to cluster store "
                        f"({len(tokens)} tokens, lora={lora_id or 'base'})",
                        source="llm-prefix-store",
                        labels={"replica": self.replica,
                                "deployment": self.deployment,
                                "tokens": str(len(tokens))})
        except Exception:
            pass
        if not wait:
            return True
        if out is None:
            return False
        ack = wire.AckMsg.decode(out[0])
        return bool(ack.ok)

    def lookup_pages(self, digests: Sequence[bytes], *, lora_id: str = "",
                     weights_version: int = 0) -> List[dict]:
        """Fetch the contiguous run of spilled blocks starting at
        digests[0]. Returns [] on miss/any failure; on success, a list of
        {tokens, k, v} dicts (callers still verify tokens against their own
        prompt before scattering — the adoption-side anti-forgery check)."""
        from ray_tpu.runtime import wire

        if not digests:
            return []
        import time

        from ray_tpu.util import tracing

        m = wire.PrefixLookupMsg(
            digests=[bytes(d) for d in digests], lora_id=lora_id or "",
            weights_version=int(weights_version),
            block_size=self.block_size, want_payload=True,
            replica=self.replica).encode()
        t0 = time.time()
        out = self._call("prefix_lookup", m)
        tracing.record_span("llm:prefix_fetch", "llm", t0, time.time(),
                            digests=len(digests), hit=out is not None,
                            replica=self.replica)
        if out is None:
            return []
        m_reply, payload = out
        reply = wire.PrefixLookupReplyMsg.decode(bytes(m_reply))
        if not reply.found or not reply.entries:
            return []
        try:
            triples = decode_all(payload)
        except TruncatedSpillError:
            # Whole-or-nothing: a torn reply adopts NOTHING.
            self.errors += 1
            return []
        if len(triples) != len(reply.entries):
            self.errors += 1
            return []
        results = []
        for ent, (_, k, v) in zip(reply.entries, triples):
            if ent.weights_version != int(weights_version):
                self.stale_rejected += 1
                self._stale_metric()
                break
            results.append({"tokens": list(ent.token_ids), "k": k, "v": v,
                            "lora_id": ent.lora_id,
                            "weights_version": ent.weights_version})
        if results:
            self.adopted_blocks += len(results)
            try:
                from ray_tpu.runtime import events, metric_defs

                metric_defs.LLM_PREFIX_ADOPTIONS.inc(
                    len(results), tags={"tier": "store"})
                events.emit(events.LLM_PREFIX_ADOPTED,
                            f"adopted {len(results)} spilled prefix "
                            f"block(s) from the cluster store",
                            source="llm-prefix-store",
                            labels={"replica": self.replica,
                                    "deployment": self.deployment,
                                    "blocks": str(len(results))})
            except Exception:
                pass
        return results

    def lookup_owner(self, digests: Sequence[bytes], *, lora_id: str = "",
                     weights_version: int = 0) -> Optional[dict]:
        """Metadata-only probe (router fallback): does the cluster hold this
        prefix, and which live replica touched it last?"""
        from ray_tpu.runtime import wire

        if not digests:
            return None
        m = wire.PrefixLookupMsg(
            digests=[bytes(d) for d in digests], lora_id=lora_id or "",
            weights_version=int(weights_version),
            block_size=self.block_size, want_payload=False).encode()
        out = self._call("prefix_lookup", m)
        if out is None:
            return None
        reply = wire.PrefixLookupReplyMsg.decode(bytes(out[0]))
        if not reply.found or not reply.entries:
            return None
        ent = reply.entries[-1]
        return {"owner_replica": ent.owner_replica,
                "n_blocks": len(reply.entries), "n_tokens": ent.n_tokens}

    def purge(self, *, owner_replica: str = "", node_id: bytes = b"",
              deployment: str = "", digests: Sequence[bytes] = (),
              below_weights_version: int = 0,
              clear_owner_only: bool = False, wait: bool = False) -> int:
        """Prune the table. `clear_owner_only` blanks live-owner hints
        (replica eject/death: the pages — GCS-homed — stay adoptable, but
        no stale owner hit may route to a dead or re-registered replica);
        otherwise matching entries are dropped outright (deployment
        deletion, stale-weights GC). Returns rows touched, or -1 when fired
        without waiting."""
        from ray_tpu.runtime import wire

        m = wire.PrefixPurgeMsg(
            owner_replica=owner_replica, node_id=bytes(node_id),
            deployment=deployment, digests=[bytes(d) for d in digests],
            below_weights_version=int(below_weights_version),
            clear_owner_only=bool(clear_owner_only)).encode()
        out = self._call("prefix_purge", m, wait=wait)
        if not wait or out is None:
            return -1
        reply = wire.PrefixPurgeReplyMsg.decode(bytes(out[0]))
        return int(reply.purged + reply.owners_cleared)

    def _stale_metric(self):
        try:
            from ray_tpu.runtime import metric_defs

            metric_defs.LLM_PREFIX_STALE_REJECTED.inc()
        except Exception:
            pass

    def stats(self) -> Dict[str, int]:
        return {"published": self.published,
                "adopted_blocks": self.adopted_blocks,
                "stale_rejected": self.stale_rejected,
                "errors": self.errors}
