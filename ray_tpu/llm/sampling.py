"""Token sampling."""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np


@dataclasses.dataclass
class SamplingParams:
    temperature: float = 0.0          # 0 = greedy
    top_k: int = 0                    # 0 = disabled
    top_p: float = 1.0
    max_tokens: int = 64
    stop_token_ids: Optional[List[int]] = None
    repetition_penalty: float = 1.0
    seed: Optional[int] = None


def sample(logits: np.ndarray, params: SamplingParams,
           prev_tokens: Optional[np.ndarray] = None) -> int:
    logits = np.asarray(logits, dtype=np.float64).copy()
    if params.repetition_penalty != 1.0 and prev_tokens is not None \
            and prev_tokens.size:
        seen = np.unique(prev_tokens)
        pos = logits[seen] > 0
        logits[seen[pos]] /= params.repetition_penalty
        logits[seen[~pos]] *= params.repetition_penalty
    if params.temperature <= 0.0:
        return int(np.argmax(logits))
    logits /= params.temperature
    if params.top_k > 0:
        kth = np.partition(logits, -params.top_k)[-params.top_k]
        logits[logits < kth] = -np.inf
    if params.top_p < 1.0:
        order = np.argsort(logits)[::-1]
        probs = _softmax(logits[order])
        keep = np.cumsum(probs) <= params.top_p
        keep[0] = True
        cut = order[~keep]
        logits[cut] = -np.inf
    probs = _softmax(logits)
    rng = np.random.default_rng(params.seed)
    return int(rng.choice(len(probs), p=probs))


def _softmax(x: np.ndarray) -> np.ndarray:
    x = x - np.max(x[np.isfinite(x)] if np.isfinite(x).any() else x)
    e = np.exp(np.where(np.isfinite(x), x, -np.inf))
    return e / e.sum()
