"""HTTP proxy: the ingress actor.

Reference analog: python/ray/serve/_private/proxy.py:752 HTTPProxy (ASGI).
An aiohttp server in an actor: POST /<deployment> with a JSON body calls the
deployment's __call__ with the parsed payload; `{"method": ...}` in the query
string selects a different method; `?stream=1` switches to a streaming
response — the replica method must return a generator, and items flow back
as server-sent events (`data: <json>` frames, `data: [DONE]` terminator),
the reference's streaming-response path (proxy.py + router streaming over
ReportGeneratorItemReturns).
"""

from __future__ import annotations

import asyncio
import json
import threading
from typing import Dict, Optional

import ray_tpu


class HTTPProxyActor:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._ready = threading.Event()
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="serve-http-proxy")
        self._thread.start()
        self._ready.wait(timeout=30)

    def address(self):
        return (self.host, self.port)

    def _serve(self):
        from aiohttp import web

        from ray_tpu.serve.handle import DeploymentHandle

        handles = {}

        async def stream_dispatch(request, handle, payload):
            import ray_tpu

            loop = asyncio.get_event_loop()
            resp = web.StreamResponse(headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache"})
            await resp.prepare(request)
            _SENTINEL = object()

            def start():
                return handle.remote_stream(payload)

            def next_item(gen):
                # Bounded wait: a stalled replica must not pin an executor
                # thread forever (the non-stream path bounds at 300s too).
                try:
                    return gen.next(timeout=300)
                except StopIteration:
                    return _SENTINEL

            try:
                gen = await loop.run_in_executor(None, start)
                while True:
                    ref = await loop.run_in_executor(None, next_item, gen)
                    if ref is _SENTINEL:
                        break
                    item = await loop.run_in_executor(
                        None, lambda: ray_tpu.get(ref, timeout=300))
                    await resp.write(b"data: "
                                     + json.dumps(item, default=repr).encode()
                                     + b"\n\n")
            except Exception as e:  # noqa: BLE001
                await resp.write(b"data: "
                                 + json.dumps({"error": repr(e)}).encode()
                                 + b"\n\n")
            await resp.write(b"data: [DONE]\n\n")
            await resp.write_eof()
            return resp

        # name -> (config_version, expires, fn|None). Validity is
        # PUSH-KEYED: while the ConfigWatcher subscription is healthy, an
        # entry is fresh iff its version matches the controller's latest
        # pushed version — a redeploy invalidates in one push (<100 ms),
        # no TTL wait. The TTL only governs the degraded mode (watcher
        # down / no event seen for this deployment yet).
        adapter_cache: Dict[str, tuple] = {}
        # Controller-outage throttle: after a failed lookup the stale entry
        # serves unconditionally until the deadline, even when a push says
        # it is outdated — otherwise every request during an outage pays a
        # blocking 30 s controller round-trip before serving stale.
        lookup_backoff: Dict[str, float] = {}

        def _cache_hit(name: str):
            import time as time_mod

            from ray_tpu.serve.config_watcher import ConfigWatcher

            hit = adapter_cache.get(name)
            if hit is None:
                return None
            now = time_mod.monotonic()
            if lookup_backoff.get(name, 0.0) > now:
                return hit
            watcher = ConfigWatcher.get()
            pushed = watcher.version(name)
            if watcher.healthy and pushed is not None:
                return hit if hit[0] == pushed else None
            return hit if hit[1] > now else None

        def _adapter_for(name: str):
            """Deployment's declared http_adapter. An unknown adapter NAME
            raises (misconfiguration must surface, not silently fall back
            to raw JSON); a transient controller RPC failure reuses the
            stale cache entry when one exists."""
            import time as time_mod

            from ray_tpu.serve import http_adapters
            from ray_tpu.serve.api import _get_controller
            from ray_tpu.serve.config_watcher import ConfigWatcher

            ConfigWatcher.get().ensure_started()
            now = time_mod.monotonic()
            hit = _cache_hit(name)
            if hit is not None:
                return hit[2]
            stale = adapter_cache.get(name)
            try:
                adapter_name, version = None, -1
                for d in ray_tpu.get(
                        _get_controller().list_deployments.remote(),
                        timeout=30):
                    if d["name"] == name:
                        adapter_name = d["config"].get("http_adapter")
                        version = d.get("version", -1)
                        break
            except Exception:
                if stale is not None:
                    # Stale beats changing request semantics; arm the
                    # outage backoff so the outage costs one probe per
                    # second, not one blocking 30 s lookup per request.
                    lookup_backoff[name] = now + 1.0
                    return stale[2]
                raise
            lookup_backoff.pop(name, None)
            fn = http_adapters.get(adapter_name) if adapter_name else None
            adapter_cache[name] = (version, now + 5.0, fn)
            return fn

        async def dispatch(request: "web.Request"):
            name = request.match_info["deployment"]
            method = request.query.get("method", "__call__")
            key = (name, method)
            if key not in handles:
                handles[key] = DeploymentHandle(name, method)
            # Cache hit resolves inline (no executor hop on the hot path);
            # only a miss/invalidation pays the controller round-trip.
            hit = _cache_hit(name)
            if hit is not None:
                adapter = hit[2]
            else:
                try:
                    adapter = await asyncio.get_event_loop().run_in_executor(
                        None, _adapter_for, name)
                except ValueError as e:  # unknown adapter name: config bug
                    return web.json_response({"error": str(e)}, status=500)
                except Exception as e:
                    return web.json_response(
                        {"error": f"adapter lookup failed: {e!r}"},
                        status=503)
            if adapter is not None:
                from ray_tpu.serve.http_adapters import HTTPRequest

                body = await request.read()
                try:
                    payload = adapter(HTTPRequest(
                        body, request.content_type or "",
                        dict(request.query)))
                except Exception as e:
                    return web.json_response(
                        {"error": f"http_adapter failed: {e!r}"}, status=400)
            else:
                try:
                    payload = await request.json()
                except Exception:
                    payload = (await request.read()).decode() or None
            handle = handles[key]
            if request.query.get("stream") in ("1", "true"):
                return await stream_dispatch(request, handle, payload)
            loop = asyncio.get_event_loop()
            try:
                # Handle calls are sync (they ride the driver RPC thread);
                # run in executor to keep the proxy loop free.
                result = await loop.run_in_executor(
                    None, lambda: handle.remote(payload).result(timeout=300))
            except ValueError as e:
                return web.json_response({"error": str(e)}, status=404)
            except Exception as e:  # noqa: BLE001
                return web.json_response({"error": repr(e)}, status=500)
            if isinstance(result, (dict, list, str, int, float)) or result is None:
                return web.json_response({"result": result})
            return web.Response(body=bytes(result))

        async def healthz(request):
            return web.json_response({"status": "ok"})

        async def run():
            app = web.Application()
            app.router.add_get("/-/healthz", healthz)
            app.router.add_post("/{deployment}", dispatch)
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, self.host, self.port)
            await site.start()
            self.port = site._server.sockets[0].getsockname()[1]
            self._ready.set()
            await asyncio.Event().wait()

        asyncio.new_event_loop().run_until_complete(run())
