"""HTTP proxy: the ingress actor.

Reference analog: python/ray/serve/_private/proxy.py:752 HTTPProxy (ASGI).
An aiohttp server in an actor: POST /<deployment> with a JSON body calls the
deployment's __call__ with the parsed payload; `{"method": ...}` in the query
string selects a different method.
"""

from __future__ import annotations

import asyncio
import json
import threading
from typing import Optional


class HTTPProxyActor:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._ready = threading.Event()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        self._ready.wait(timeout=30)

    def address(self):
        return (self.host, self.port)

    def _serve(self):
        from aiohttp import web

        from ray_tpu.serve.handle import DeploymentHandle

        handles = {}

        async def dispatch(request: "web.Request"):
            name = request.match_info["deployment"]
            method = request.query.get("method", "__call__")
            key = (name, method)
            if key not in handles:
                handles[key] = DeploymentHandle(name, method)
            try:
                payload = await request.json()
            except Exception:
                payload = (await request.read()).decode() or None
            handle = handles[key]
            loop = asyncio.get_event_loop()
            try:
                # Handle calls are sync (they ride the driver RPC thread);
                # run in executor to keep the proxy loop free.
                result = await loop.run_in_executor(
                    None, lambda: handle.remote(payload).result(timeout=300))
            except ValueError as e:
                return web.json_response({"error": str(e)}, status=404)
            except Exception as e:  # noqa: BLE001
                return web.json_response({"error": repr(e)}, status=500)
            if isinstance(result, (dict, list, str, int, float)) or result is None:
                return web.json_response({"result": result})
            return web.Response(body=bytes(result))

        async def healthz(request):
            return web.json_response({"status": "ok"})

        async def run():
            app = web.Application()
            app.router.add_get("/-/healthz", healthz)
            app.router.add_post("/{deployment}", dispatch)
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, self.host, self.port)
            await site.start()
            self.port = site._server.sockets[0].getsockname()[1]
            self._ready.set()
            await asyncio.Event().wait()

        asyncio.new_event_loop().run_until_complete(run())
