"""gRPC ingress for serve.

Reference analog: python/ray/serve/_private/proxy.py:532 gRPCProxy. The
reference compiles user protos; ours exposes a fixed generic service
(`ray_tpu.serve.ServeAPI`) with JSON-over-bytes messages so no protoc step
is needed:

  * Predict       (unary-unary):  request bytes = JSON
        {"deployment": str, "method": str = "__call__", "payload": any}
    reply bytes = JSON {"result": any} or {"error": str}
  * PredictStream (unary-stream): same request; the replica method must
    return a generator; each yielded item streams back as one JSON frame
    (the reference's streaming path over ReportGeneratorItemReturns).

Runs as a plain object inside the proxy actor process next to the HTTP
proxy, sharing the DeploymentHandle routing (power-of-two replica choice).
"""

from __future__ import annotations

import json
from concurrent import futures as _futures
from typing import Dict

SERVICE = "ray_tpu.serve.ServeAPI"


class GrpcProxy:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        import grpc

        from ray_tpu.serve.handle import DeploymentHandle

        self._handles: Dict[tuple, DeploymentHandle] = {}

        def handle_for(deployment: str, method: str) -> DeploymentHandle:
            key = (deployment, method)
            if key not in self._handles:
                self._handles[key] = DeploymentHandle(deployment, method)
            return self._handles[key]

        def _parse(request: bytes):
            req = json.loads(request)
            return (req["deployment"], req.get("method", "__call__"),
                    req.get("payload"), float(req.get("timeout", 300.0)))

        def predict(request: bytes, context) -> bytes:
            try:
                deployment, method, payload, timeout = _parse(request)
                result = handle_for(deployment, method).remote(
                    payload).result(timeout=timeout)
                return json.dumps({"result": result}, default=repr).encode()
            except Exception as e:  # noqa: BLE001 — errors ride the reply
                return json.dumps({"error": repr(e)}).encode()

        def predict_stream(request: bytes, context):
            try:
                deployment, method, payload, timeout = _parse(request)
                gen = handle_for(deployment, method).remote_stream(payload)
                import ray_tpu

                for ref in gen:
                    item = ray_tpu.get(ref, timeout=timeout)
                    yield json.dumps({"item": item}, default=repr).encode()
            except Exception as e:  # noqa: BLE001
                yield json.dumps({"error": repr(e)}).encode()

        handler = grpc.method_handlers_generic_handler(SERVICE, {
            "Predict": grpc.unary_unary_rpc_method_handler(predict),
            "PredictStream": grpc.unary_stream_rpc_method_handler(
                predict_stream),
        })
        self._server = grpc.server(
            _futures.ThreadPoolExecutor(max_workers=8))
        self._server.add_generic_rpc_handlers((handler,))
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        self.host = host
        self._server.start()

    def address(self):
        return (self.host, self.port)

    def stop(self):
        self._server.stop(grace=1.0)


class GrpcServeClient:
    """Minimal client for the generic service (tests / SDK)."""

    def __init__(self, address: str):
        import grpc

        self._channel = grpc.insecure_channel(address)
        self._predict = self._channel.unary_unary(f"/{SERVICE}/Predict")
        self._stream = self._channel.unary_stream(f"/{SERVICE}/PredictStream")

    def predict(self, deployment: str, payload, method: str = "__call__",
                timeout: float = 300.0):
        # The timeout rides the request too: the server bounds its backend
        # wait with it, so the client deadline governs end to end.
        reply = json.loads(self._predict(
            json.dumps({"deployment": deployment, "method": method,
                        "payload": payload, "timeout": timeout}).encode(),
            timeout=timeout))
        if "error" in reply:
            raise RuntimeError(reply["error"])
        return reply["result"]

    def predict_stream(self, deployment: str, payload,
                       method: str = "__call__", timeout: float = 300.0):
        for frame in self._stream(
                json.dumps({"deployment": deployment, "method": method,
                            "payload": payload,
                            "timeout": timeout}).encode(), timeout=timeout):
            item = json.loads(frame)
            if "error" in item:
                raise RuntimeError(item["error"])
            yield item["item"]

    def close(self):
        self._channel.close()
