"""Push-based serve config propagation.

Reference analog: python/ray/serve/_private/long_poll.py:204 LongPollHost —
the controller PUSHES config changes to proxies/handles instead of being
polled. Ours rides the GCS pubsub channel (one-way KIND_PUSH frames,
runtime/rpc.py): the ServeController publishes
{deployment, version, event} on every deploy/scale/delete, and every
process holding handles runs one shared ConfigWatcher subscription that
records the freshest version per deployment. DeploymentHandle and the HTTP
proxy consult the watcher before each request: a version newer than what
they routed with triggers an immediate (<100 ms end-to-end) refresh; no
polling loop runs while the subscription is healthy. If the subscription
is down or has no entry yet (subscribed after the event), callers fall
back to the old time-based refresh interval — push is the fast path,
polling only the degraded mode.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Optional

logger = logging.getLogger(__name__)

CHANNEL = "serve_config"


class ConfigWatcher:
    """Per-process singleton subscription to the serve_config channel."""

    _instance: Optional["ConfigWatcher"] = None
    _lock = threading.Lock()

    def __init__(self):
        self.versions: Dict[str, int] = {}
        self._client = None
        self._starting = False
        self._connected = False
        self._retry_at = 0.0
        self._start_lock = threading.Lock()

    @classmethod
    def get(cls) -> "ConfigWatcher":
        with cls._lock:
            if cls._instance is None:
                cls._instance = ConfigWatcher()
            return cls._instance

    @classmethod
    def reset(cls):
        """Test hook: drop the singleton (e.g. across cluster restarts)."""
        with cls._lock:
            inst, cls._instance = cls._instance, None
        if inst is not None and inst._client is not None:
            try:
                from ray_tpu.core.worker import global_worker

                global_worker().io.spawn(inst._client.close())
            except Exception:
                pass

    # ---- subscription ----------------------------------------------------

    def ensure_started(self):
        """NON-BLOCKING: kick the subscription machinery and return. The
        caller (a handle on the request path) must never wait on GCS —
        while the stream isn't healthy it simply uses the time-based
        refresh fallback."""
        if self._client is not None:
            if self._client._dead and not self._client._closed:
                # Push-only connections never issue calls, so a dead GCS
                # link (GCS restart) would stay dead: kick the client's
                # auto-reconnect (redials + resubscribes via on_reconnect)
                # with a cheap call. Handles keep using the time-based
                # fallback until the stream is healthy again.
                try:
                    from ray_tpu.core.worker import global_worker

                    global_worker().io.spawn(
                        self._client.call("subscribe", channels=[CHANNEL]))
                except Exception:
                    pass
            return
        with self._start_lock:
            if self._starting or time.monotonic() < self._retry_at:
                return
            self._starting = True
        try:
            from ray_tpu.core.worker import global_worker
            from ray_tpu.runtime.rpc import RpcClient

            core = global_worker()

            async def on_push(method, data):
                if method != "pubsub" or data.get("channel") != CHANNEL:
                    return
                msg = data.get("message") or {}
                name, version = msg.get("deployment"), msg.get("version")
                if name is None or version is None:
                    return
                if version > self.versions.get(name, -1):
                    self.versions[name] = version

            async def resub(client):
                await client._call_once("subscribe", 30,
                                        dict(channels=[CHANNEL]))

            async def connect():
                try:
                    client = RpcClient(core.gcs.host, core.gcs.port,
                                       on_push=on_push, auto_reconnect=True,
                                       on_reconnect=resub)
                    await client.connect(timeout=30)
                    await client.call("subscribe", channels=[CHANNEL])
                    self._client = client
                    self._connected = True
                except Exception:
                    logger.warning(
                        "serve config watcher failed to start; handles "
                        "fall back to periodic refresh", exc_info=True)
                    # Backoff before the next background attempt: a GCS
                    # outage must not spawn a connect per request.
                    self._retry_at = time.monotonic() + 5.0
                finally:
                    self._starting = False

            core.io.spawn(connect())
        except Exception:
            logger.exception("serve config watcher spawn failed")
            self._starting = False

    @property
    def healthy(self) -> bool:
        return (self._connected and self._client is not None
                and not self._client._dead)

    def version(self, deployment: str) -> Optional[int]:
        """Freshest pushed version, or None if no event seen yet."""
        return self.versions.get(deployment)


def publish_change(deployment: str, version: int, event: str):
    """Controller side: fire-and-forget push to every subscriber."""
    try:
        from ray_tpu.core.worker import global_worker

        core = global_worker()
        core.io.spawn(core.gcs.call(
            "publish", channel=CHANNEL,
            message={"deployment": deployment, "version": version,
                     "event": event, "ts": time.time()}))
    except Exception:
        logger.exception("serve config publish failed (%s %s)",
                         deployment, event)
