"""Deployment definition + replica actor.

Reference analog: python/ray/serve/deployment.py (@serve.deployment) and
replica.py (user-code runner). A deployment wraps a class (or function);
replicas are actors created by the controller; requests arrive as ordinary
actor calls (`handle_request`).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional


@dataclasses.dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_ongoing_requests: int = 16
    num_cpus: float = 0.0
    num_tpus: float = 0.0
    resources: Optional[Dict[str, float]] = None
    # Reference analog: serve autoscaling_policy.py — replica-queue-driven
    # target tracking. Keys: min_replicas, max_replicas,
    # target_ongoing_requests, interval_s, downscale_delay_s.
    autoscaling_config: Optional[Dict] = None
    # Registry name of an ingress adapter (serve/http_adapters.py): the
    # HTTP proxy converts the request before dispatch; handle callers and
    # gRPC are unaffected (reference: serve http_adapters).
    http_adapter: Optional[str] = None


class Deployment:
    def __init__(self, func_or_class: Any, name: str, config: DeploymentConfig,
                 init_args: tuple = (), init_kwargs: Optional[dict] = None):
        self.func_or_class = func_or_class
        self.name = name
        self.config = config
        self.init_args = init_args
        self.init_kwargs = init_kwargs or {}

    def options(self, *, name: Optional[str] = None,
                num_replicas: Optional[int] = None,
                max_ongoing_requests: Optional[int] = None,
                num_cpus: Optional[float] = None,
                num_tpus: Optional[float] = None,
                resources: Optional[Dict[str, float]] = None,
                autoscaling_config: Optional[Dict] = None,
                http_adapter: Optional[str] = None) -> "Deployment":
        cfg = dataclasses.replace(
            self.config,
            num_replicas=num_replicas if num_replicas is not None
            else self.config.num_replicas,
            max_ongoing_requests=max_ongoing_requests if max_ongoing_requests
            is not None else self.config.max_ongoing_requests,
            num_cpus=num_cpus if num_cpus is not None else self.config.num_cpus,
            num_tpus=num_tpus if num_tpus is not None else self.config.num_tpus,
            resources=resources if resources is not None else self.config.resources,
            autoscaling_config=autoscaling_config if autoscaling_config
            is not None else self.config.autoscaling_config,
            http_adapter=http_adapter if http_adapter is not None
            else self.config.http_adapter)
        return Deployment(self.func_or_class, name or self.name, cfg,
                          self.init_args, self.init_kwargs)

    def bind(self, *args, **kwargs) -> "Deployment":
        """Bind init args (the graph-building API)."""
        return Deployment(self.func_or_class, self.name, self.config, args, kwargs)


def deployment(func_or_class=None, *, name: Optional[str] = None,
               num_replicas: int = 1, max_ongoing_requests: int = 16,
               num_cpus: float = 0.0, num_tpus: float = 0.0,
               resources: Optional[Dict[str, float]] = None,
               autoscaling_config: Optional[Dict] = None,
               http_adapter: Optional[str] = None):
    def wrap(target):
        return Deployment(
            target, name or target.__name__,
            DeploymentConfig(num_replicas, max_ongoing_requests, num_cpus,
                             num_tpus, resources, autoscaling_config,
                             http_adapter))

    if func_or_class is not None:
        return wrap(func_or_class)
    return wrap


class ReplicaActor:
    """Hosts the user callable. One per replica."""

    def __init__(self, target_payload: bytes, init_args, init_kwargs):
        import cloudpickle

        target = cloudpickle.loads(target_payload)
        if isinstance(target, type):
            self.callable = target(*init_args, **init_kwargs)
        else:
            self.callable = target
        self._ongoing = 0

    def handle_request(self, method: str, args: tuple, kwargs: dict):
        self._ongoing += 1
        try:
            fn = self.callable if method == "__call__" and not isinstance(
                self.callable, object.__class__) else None
            if method == "__call__":
                fn = self.callable if callable(self.callable) else None
                if fn is None:
                    raise AttributeError("deployment target is not callable")
            else:
                fn = getattr(self.callable, method)
            return fn(*args, **kwargs)
        finally:
            self._ongoing -= 1

    def queue_len(self) -> int:
        return self._ongoing

    def health_check(self) -> bool:
        check = getattr(self.callable, "check_health", None)
        if check is not None:
            check()
        return True
