"""ServeController: deployment/replica lifecycle.

Reference analog: python/ray/serve/_private/controller.py:84 ServeController
+ deployment_state.py:1248 (replica state machine) + long_poll.py:204 config
propagation. Ours: a named actor owning the replica actors per deployment;
handles pull the replica list with a version number and refresh on change
(the long-poll pattern collapsed to versioned polling).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import ray_tpu


class ServeController:
    CONTROLLER_NAME = "SERVE_CONTROLLER"

    def __init__(self):
        # deployment name -> {"replicas": [handles], "config", "version"}
        self.deployments: Dict[str, Dict] = {}
        self.version = 0

    def deploy(self, name: str, target_payload: bytes, config: dict,
               init_args_payload: bytes) -> bool:
        import cloudpickle

        from ray_tpu.serve.deployment import ReplicaActor

        init_args, init_kwargs = cloudpickle.loads(init_args_payload)
        existing = self.deployments.get(name)
        if existing is not None:
            for r in existing["replicas"]:
                try:
                    ray_tpu.kill(r)
                except Exception:
                    pass
        Replica = ray_tpu.remote(ReplicaActor)
        replicas = []
        for i in range(config["num_replicas"]):
            replicas.append(Replica.options(
                num_cpus=config.get("num_cpus", 0),
                num_tpus=config.get("num_tpus", 0),
                resources=config.get("resources") or {}).remote(
                target_payload, init_args, init_kwargs))
        # Wait until replicas construct successfully.
        ray_tpu.get([r.health_check.remote() for r in replicas], timeout=300)
        self.version += 1
        self.deployments[name] = {"replicas": replicas, "config": config,
                                  "version": self.version}
        return True

    def get_replicas(self, name: str) -> dict:
        d = self.deployments.get(name)
        if d is None:
            return {"found": False, "version": self.version}
        return {"found": True, "replicas": d["replicas"],
                "version": d["version"]}

    def list_deployments(self) -> List[dict]:
        return [{"name": k, "num_replicas": len(v["replicas"]),
                 "config": v["config"]} for k, v in self.deployments.items()]

    def delete_deployment(self, name: str) -> bool:
        d = self.deployments.pop(name, None)
        if d is None:
            return False
        for r in d["replicas"]:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        self.version += 1
        return True

    def global_version(self) -> int:
        return self.version
