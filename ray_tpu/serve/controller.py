"""ServeController: deployment/replica lifecycle.

Reference analog: python/ray/serve/_private/controller.py:84 ServeController
+ deployment_state.py:1248 (replica state machine) + long_poll.py:204 config
propagation. Ours: a named actor owning the replica actors per deployment;
every config change (deploy/scale/delete) is PUSHED to proxies and handles
over the GCS pubsub channel (serve/config_watcher.py — the LongPollHost
analog riding KIND_PUSH frames); the version number doubles as the
fallback refresh key when a subscriber's push stream is down.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import ray_tpu


class ServeController:
    CONTROLLER_NAME = "SERVE_CONTROLLER"

    def __init__(self):
        import threading

        # deployment name -> {"replicas": [handles], "config", "version"}
        self.deployments: Dict[str, Dict] = {}
        self.version = 0
        self._lock = threading.Lock()
        self._autoscale_thread = threading.Thread(
            target=self._autoscale_loop, daemon=True,
            name="serve-autoscale")
        self._autoscale_thread.start()

    def deploy(self, name: str, target_payload: bytes, config: dict,
               init_args_payload: bytes) -> bool:
        import cloudpickle

        from ray_tpu.serve.deployment import ReplicaActor

        init_args, init_kwargs = cloudpickle.loads(init_args_payload)
        existing = self.deployments.get(name)
        if existing is not None:
            for r in existing["replicas"]:
                try:
                    ray_tpu.kill(r)
                except Exception:
                    pass
        ac = config.get("autoscaling")
        n = (max(int(ac.get("min_replicas", 1)), 1) if ac
             else config["num_replicas"])
        Replica = ray_tpu.remote(ReplicaActor)
        replicas = []
        for i in range(n):
            replicas.append(Replica.options(
                num_cpus=config.get("num_cpus", 0),
                num_tpus=config.get("num_tpus", 0),
                max_concurrency=config.get("max_ongoing_requests", 16),
                resources=config.get("resources") or {}).remote(
                target_payload, init_args, init_kwargs))
        # Wait until replicas construct successfully.
        ray_tpu.get([r.health_check.remote() for r in replicas], timeout=300)
        with self._lock:
            self.version += 1
            version = self.version
            self.deployments[name] = {
                "replicas": replicas, "config": config,
                "version": version, "target_payload": target_payload,
                "init_args": init_args, "init_kwargs": init_kwargs}
        self._publish(name, version, "deployed")
        self._snapshot_to_kv()
        return True

    @staticmethod
    def _publish(name: str, version: int, event: str):
        """Push the change to proxies/handles (LongPollHost analog)."""
        from ray_tpu.serve.config_watcher import publish_change

        publish_change(name, version, event)

    def _snapshot_to_kv(self):
        """Dashboard feed: deployment status snapshot in the GCS KV
        (reference: dashboard serve module reads controller state)."""
        import json as json_mod

        try:
            from ray_tpu.core.worker import global_worker

            with self._lock:  # autoscaler thread mutates concurrently
                snap = [{"name": k, "num_replicas": len(v["replicas"]),
                         "version": v["version"],
                         "autoscaling": bool(v["config"].get("autoscaling")),
                         "max_ongoing_requests":
                             v["config"].get("max_ongoing_requests", 16)}
                        for k, v in self.deployments.items()]
            core = global_worker()
            core.io.spawn(core.gcs.call(
                "kv_put", key=b"serve:deployments",
                value=json_mod.dumps(snap).encode(), overwrite=True))
        except Exception:
            pass

    # ---- autoscaling (autoscaling_policy.py analog) ----------------------

    def _autoscale_loop(self):
        import logging

        log = logging.getLogger(__name__)
        from ray_tpu.config import cfg

        while True:
            time.sleep(cfg().serve_autoscale_interval_s)
            try:
                self._autoscale_once()
            except Exception:
                log.exception("serve autoscale tick failed")

    def _autoscale_once(self):
        import math

        for name in list(self.deployments):
            with self._lock:
                d = self.deployments.get(name)
                if d is None:
                    continue
                ac = d["config"].get("autoscaling")
                replicas = list(d["replicas"])
            if not ac or not replicas:
                continue
            # Queue depth via the worker's direct actor_stats RPC (served on
            # its IO loop): includes tasks queued behind busy exec threads,
            # and never blocks behind user code the way an actor-method probe
            # (queue_len) would. Reference analog: replica queue-length
            # reporting into autoscaling_state.py.
            from ray_tpu.core.worker import global_worker

            core = global_worker()
            try:
                stats = core.actor_stats_many(
                    [r._actor_id for r in replicas], timeout=3)
            except Exception:
                continue
            queues = [int(s.get("pending", 0)) for s in stats if s is not None]
            if not queues:
                continue  # every replica unreachable (restarting/dead)
            target = max(float(ac.get("target_ongoing_requests", 2)), 0.1)
            desired = math.ceil(sum(queues) / target) or 0
            desired = max(int(ac.get("min_replicas", 1)),
                          min(int(ac.get("max_replicas", len(replicas))),
                              desired))
            now = time.monotonic()
            if desired > len(replicas):
                self._scale_to(name, desired)
                d["last_scale"] = now
            elif desired < len(replicas):
                # Downscale only after a quiet delay (thrash guard).
                delay = float(ac.get("downscale_delay_s", 5.0))
                pending_since = d.setdefault("downscale_since", now)
                if now - pending_since >= delay:
                    self._scale_to(name, desired)
                    d.pop("downscale_since", None)
                continue
            d.pop("downscale_since", None)

    def _scale_to(self, name: str, desired: int):
        from ray_tpu.serve.deployment import ReplicaActor

        # Snapshot under the lock; start replicas and wait for health checks
        # OUTSIDE it (a hung constructor must not block deploy()/routing of
        # every other deployment); re-acquire to commit, re-validating that
        # the deployment still exists at the same version.
        with self._lock:
            d = self.deployments.get(name)
            if d is None:
                return
            current = len(d["replicas"])
            version = d["version"]
            cfg = d["config"]
            payload = d["target_payload"]
            init_args, init_kwargs = d["init_args"], d["init_kwargs"]
        if desired == current:
            return
        if desired > current:
            Replica = ray_tpu.remote(ReplicaActor)
            new = [Replica.options(
                num_cpus=cfg.get("num_cpus", 0),
                num_tpus=cfg.get("num_tpus", 0),
                max_concurrency=cfg.get("max_ongoing_requests", 16),
                resources=cfg.get("resources") or {}).remote(
                    payload, init_args, init_kwargs)
                for _ in range(desired - current)]
            try:
                ray_tpu.get([r.health_check.remote() for r in new],
                            timeout=300)
            except Exception:
                for r in new:
                    try:
                        ray_tpu.kill(r)
                    except Exception:
                        pass
                raise
            with self._lock:
                d = self.deployments.get(name)
                if d is None or d["version"] != version:
                    # Deployment replaced/deleted while we were starting.
                    for r in new:
                        try:
                            ray_tpu.kill(r)
                        except Exception:
                            pass
                    return
                d["replicas"].extend(new)
                self.version += 1
                d["version"] = self.version
                new_version = self.version
            self._publish(name, new_version, "scaled_up")
            self._snapshot_to_kv()
        else:
            with self._lock:
                d = self.deployments.get(name)
                if d is None or d["version"] != version:
                    return
                victims = d["replicas"][desired:]
                d["replicas"] = d["replicas"][:desired]
                self.version += 1
                d["version"] = self.version
                new_version = self.version
            self._publish(name, new_version, "scaled_down")
            self._snapshot_to_kv()
            for r in victims:
                try:
                    ray_tpu.kill(r)
                except Exception:
                    pass

    def scale_replicas(self, name: str, desired: int) -> bool:
        """External scaling entry point (the LLM router's replica policy
        drives this): set the replica count directly, bypassing the
        queue-depth autoscaler — which skips deployments without an
        `autoscaling` config, so the two never fight over one fleet."""
        if desired < 1:
            return False
        if name not in self.deployments:
            return False
        self._scale_to(name, int(desired))
        return True

    def remove_replica(self, name: str, actor_id_hex: str) -> bool:
        """Retire one SPECIFIC replica by actor id.

        _scale_to's downscale always trims the tail of the replica list;
        drain-based scale-down needs to kill the replica whose sessions
        were just migrated out, whichever slot it holds. The caller (LLM
        router) is responsible for having drained it first — by the time
        this runs the replica should hold no live sessions."""
        with self._lock:
            d = self.deployments.get(name)
            if d is None:
                return False
            victim = None
            for r in d["replicas"]:
                aid = getattr(r, "_actor_id", None)
                aid_hex = (bytes(aid).hex()
                           if isinstance(aid, (bytes, bytearray))
                           else str(aid))
                if aid_hex == actor_id_hex:
                    victim = r
                    break
            if victim is None:
                return False
            d["replicas"] = [r for r in d["replicas"] if r is not victim]
            self.version += 1
            d["version"] = self.version
            new_version = self.version
        self._publish(name, new_version, "scaled_down")
        self._snapshot_to_kv()
        try:
            ray_tpu.kill(victim)
        except Exception:
            pass
        return True

    def get_replicas(self, name: str) -> dict:
        d = self.deployments.get(name)
        if d is None:
            return {"found": False, "version": self.version}
        return {"found": True, "replicas": d["replicas"],
                "version": d["version"]}

    def list_deployments(self) -> List[dict]:
        return [{"name": k, "num_replicas": len(v["replicas"]),
                 "config": v["config"], "version": v["version"]}
                for k, v in self.deployments.items()]

    def delete_deployment(self, name: str) -> bool:
        d = self.deployments.pop(name, None)
        if d is None:
            return False
        for r in d["replicas"]:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        self.version += 1
        self._publish(name, self.version, "deleted")
        self._snapshot_to_kv()
        self._purge_prefix_entries(name)
        return True

    @staticmethod
    def _purge_prefix_entries(name: str):
        """Drop the deployment's rows from the GCS cluster prefix table
        (llm/prefix_store.py): unlike replica death — which only blanks the
        live-owner hint so survivors can still adopt the pages — deleting
        the deployment retires the whole fleet, so its spilled KV is freed
        outright."""
        try:
            from ray_tpu.core.worker import global_worker
            from ray_tpu.runtime import wire

            m = wire.PrefixPurgeMsg(deployment=name).encode()
            core = global_worker()
            core.io.spawn(core.gcs.call_raw("prefix_purge", m=m))
        except Exception:
            pass

    def global_version(self) -> int:
        return self.version
