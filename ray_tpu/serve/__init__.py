from ray_tpu.serve.api import (  # noqa: F401
    delete,
    get_deployment_handle,
    run,
    shutdown,
    start_grpc_proxy,
    start_http_proxy,
    status,
)
from ray_tpu.serve.batching import serve_batch as batch  # noqa: F401
from ray_tpu.serve.deployment import Deployment, deployment  # noqa: F401
from ray_tpu.serve.handle import DeploymentHandle  # noqa: F401
from ray_tpu.serve import http_adapters  # noqa: F401
from ray_tpu.serve.multiplex import (  # noqa: F401
    Multiplexer,
    get_multiplexed_model_id,
    multiplexed,
)
