"""Serve public API.

Reference analog: python/ray/serve/api.py (serve.run:591, get_deployment_handle,
delete, status).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Union

import ray_tpu
from ray_tpu.serve.controller import ServeController
from ray_tpu.serve.deployment import Deployment
from ray_tpu.serve.handle import DeploymentHandle

_controller = None
_proxy = None
_grpc_proxy = None


def _get_controller():
    global _controller
    if _controller is None:
        try:
            _controller = ray_tpu.get_actor(ServeController.CONTROLLER_NAME)
        except ValueError:
            Controller = ray_tpu.remote(ServeController)
            _controller = Controller.options(
                name=ServeController.CONTROLLER_NAME).remote()
    return _controller


def run(target: Union[Deployment, List[Deployment]], *,
        http: bool = False, http_port: int = 0,
        local_testing_mode: bool = False) -> DeploymentHandle:
    """Deploy one or more deployments; returns a handle to the first.

    Model composition (reference: serve deployment graphs — api.py:591 with
    bound child deployments): a Deployment appearing anywhere in another's
    bound init args is deployed first and replaced by a DeploymentHandle,
    so the parent replica calls children through ordinary handles.

    local_testing_mode=True (reference:
    serve/_private/local_testing_mode.py) runs everything in-process — no
    cluster, no actors — with the same handle surface."""
    import cloudpickle

    if local_testing_mode:
        from ray_tpu.serve.local_testing import run_local

        deployments = ([target] if isinstance(target, Deployment)
                       else list(target))
        return run_local(deployments)

    controller = _get_controller()
    deployments = [target] if isinstance(target, Deployment) else list(target)
    deployed: set = set()

    def resolve(obj):
        if isinstance(obj, Deployment):
            deploy(obj)
            return DeploymentHandle(obj.name)
        if isinstance(obj, (list, tuple)):
            return type(obj)(resolve(x) for x in obj)
        if isinstance(obj, dict):
            return {k: resolve(v) for k, v in obj.items()}
        return obj

    def deploy(dep: Deployment):
        if dep.name in deployed:
            return
        deployed.add(dep.name)  # before recursing: breaks bind cycles
        init_args = tuple(resolve(a) for a in dep.init_args)
        init_kwargs = {k: resolve(v) for k, v in dep.init_kwargs.items()}
        cfg = {
            "num_replicas": dep.config.num_replicas,
            "max_ongoing_requests": dep.config.max_ongoing_requests,
            "num_cpus": dep.config.num_cpus,
            "num_tpus": dep.config.num_tpus,
            "resources": dep.config.resources,
            "autoscaling": dep.config.autoscaling_config,
            "http_adapter": dep.config.http_adapter,
        }
        ray_tpu.get(controller.deploy.remote(
            dep.name, cloudpickle.dumps(dep.func_or_class), cfg,
            cloudpickle.dumps((init_args, init_kwargs))), timeout=600)

    for dep in deployments:
        deploy(dep)
    if http:
        start_http_proxy(port=http_port)
    return DeploymentHandle(deployments[0].name)


def get_deployment_handle(name: str) -> DeploymentHandle:
    from ray_tpu.serve.local_testing import get_local_handle

    local = get_local_handle(name)
    if local is not None:
        return local
    return DeploymentHandle(name)


def status() -> List[dict]:
    from ray_tpu.serve.local_testing import _local_deployments, local_status

    if _local_deployments:
        return local_status()
    controller = _get_controller()
    return ray_tpu.get(controller.list_deployments.remote(), timeout=60)


def delete(name: str) -> bool:
    from ray_tpu.serve.local_testing import delete_local

    if delete_local(name):
        return True
    controller = _get_controller()
    return ray_tpu.get(controller.delete_deployment.remote(name), timeout=60)


def start_http_proxy(port: int = 0):
    """Start (or return) the HTTP ingress; returns (host, port)."""
    global _proxy
    if _proxy is None:
        from ray_tpu.serve.proxy import HTTPProxyActor

        Proxy = ray_tpu.remote(HTTPProxyActor)
        _proxy = Proxy.options(name="SERVE_HTTP_PROXY").remote(port=port)
    return tuple(ray_tpu.get(_proxy.address.remote(), timeout=60))


def start_grpc_proxy(port: int = 0):
    """Start (or return) the gRPC ingress (reference: proxy.py:532
    gRPCProxy); returns (host, port). See serve/grpc_proxy.py for the
    generic JSON-over-bytes service contract."""
    global _grpc_proxy
    if _grpc_proxy is None:
        from ray_tpu.serve.grpc_proxy import GrpcProxy

        Proxy = ray_tpu.remote(GrpcProxy)
        _grpc_proxy = Proxy.options(name="SERVE_GRPC_PROXY").remote(port=port)
    return tuple(ray_tpu.get(_grpc_proxy.address.remote(), timeout=60))


def shutdown():
    global _controller, _proxy, _grpc_proxy
    from ray_tpu.serve.config_watcher import ConfigWatcher
    from ray_tpu.serve.local_testing import shutdown_local

    ConfigWatcher.reset()
    shutdown_local()
    if _controller is None:
        return  # local-only session: nothing cluster-side to tear down
    for name in [d["name"] for d in status()]:
        delete(name)
    if _grpc_proxy is not None:
        try:
            ray_tpu.kill(_grpc_proxy)
        except Exception:
            pass
        _grpc_proxy = None
    if _proxy is not None:
        try:
            ray_tpu.kill(_proxy)
        except Exception:
            pass
    if _controller is not None:
        try:
            ray_tpu.kill(_controller)
        except Exception:
            pass
    _controller = None
    _proxy = None
