"""HTTP adapters: declarative request -> model-input conversion.

Reference analog: python/ray/serve/http_adapters.py (json_request,
json_to_ndarray, pandas_read_json, image_to_ndarray) applied at the
ingress. A deployment declares `http_adapter="json_to_ndarray"` and its
callable receives the converted value instead of raw JSON; non-HTTP
callers (DeploymentHandle.remote) are unaffected. Adapters are looked up
by REGISTRY NAME so the config stays a plain serializable dataclass;
custom adapters register via `register()`.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, Optional


class HTTPRequest:
    """What the proxy hands an adapter: the raw request essentials."""

    __slots__ = ("body", "content_type", "query")

    def __init__(self, body: bytes, content_type: str = "",
                 query: Optional[Dict[str, str]] = None):
        self.body = body
        self.content_type = content_type
        self.query = dict(query or {})

    def json(self) -> Any:
        return json.loads(self.body) if self.body else None

    def text(self) -> str:
        return self.body.decode("utf-8", "replace")


def json_request(request: HTTPRequest) -> Any:
    """Parsed JSON body (the default behavior, made explicit)."""
    return request.json()


def bytes_request(request: HTTPRequest) -> bytes:
    """Raw body bytes."""
    return request.body


def json_to_ndarray(request: HTTPRequest):
    """{"array": [...]} (or a bare JSON list) -> np.ndarray; optional
    "dtype" key / query param."""
    import numpy as np

    payload = request.json()
    dtype = request.query.get("dtype")
    if isinstance(payload, dict):
        dtype = payload.get("dtype", dtype)
        if "array" not in payload:
            # np.asarray(dict) would "succeed" as a 0-d object array and
            # crash the replica downstream; tell the client instead.
            raise ValueError(
                'json_to_ndarray expects {"array": [...]} or a bare JSON '
                f"list; got keys {sorted(payload)}")
        payload = payload["array"]
    arr = np.asarray(payload)
    return arr.astype(dtype) if dtype else arr


def pandas_read_json(request: HTTPRequest):
    """JSON body -> pandas DataFrame (records or column-dict orient)."""
    import pandas as pd

    payload = request.json()
    return pd.DataFrame(payload)


def image_to_ndarray(request: HTTPRequest):
    """Image bytes (png/jpeg) -> RGB ndarray."""
    import io

    import numpy as np

    try:
        from PIL import Image
    except ImportError as e:
        raise ImportError("image_to_ndarray requires Pillow") from e
    with Image.open(io.BytesIO(request.body)) as im:
        return np.asarray(im.convert("RGB"))


_REGISTRY: Dict[str, Callable[[HTTPRequest], Any]] = {
    "json_request": json_request,
    "bytes_request": bytes_request,
    "json_to_ndarray": json_to_ndarray,
    "pandas_read_json": pandas_read_json,
    "image_to_ndarray": image_to_ndarray,
}


def register(name: str, fn: Callable[[HTTPRequest], Any]) -> None:
    _REGISTRY[name] = fn


def get(name: str) -> Callable[[HTTPRequest], Any]:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown http_adapter {name!r}; known: {sorted(_REGISTRY)}"
        ) from None
