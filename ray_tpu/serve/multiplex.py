"""Model multiplexing: many models (e.g. LoRA adapters) per replica with
LRU residency.

Reference analog: python/ray/serve/multiplex.py (_ModelMultiplexWrapper,
@serve.multiplexed + get_multiplexed_model_id). A replica holds up to
``max_num_models_per_replica`` loaded models; requests route by model id,
loading on miss and evicting the least recently used (HBM is the scarce
resource on TPU — evicted model weights free device memory for the next
adapter).
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Callable, Optional

__all__ = ["Multiplexer", "multiplexed"]

_current_model_id = threading.local()


def get_multiplexed_model_id() -> Optional[str]:
    """Inside a model loader or request handler: the model id being served."""
    return getattr(_current_model_id, "value", None)


class Multiplexer:
    """LRU cache of loaded models keyed by model id."""

    def __init__(self, load_fn: Callable[[str], Any],
                 max_num_models: int = 3,
                 unload_fn: Optional[Callable[[Any], None]] = None):
        self.load_fn = load_fn
        self.unload_fn = unload_fn
        self.max_num_models = max_num_models
        self._models: "collections.OrderedDict[str, Any]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()
        self.load_count = 0
        self.evict_count = 0

    def get_model(self, model_id: str) -> Any:
        with self._lock:
            if model_id in self._models:
                self._models.move_to_end(model_id)
                return self._models[model_id]
        # Load outside the lock (device transfers are slow). If another
        # thread loaded the same id while we did, keep the existing entry
        # and unload our duplicate — dropping it silently would leak the
        # device/HBM memory multiplexing exists to manage.
        prev = getattr(_current_model_id, "value", None)
        _current_model_id.value = model_id
        try:
            model = self.load_fn(model_id)
        finally:
            _current_model_id.value = prev
        evicted = []
        duplicate = None
        with self._lock:
            if model_id in self._models:
                duplicate, model = model, self._models[model_id]
                self._models.move_to_end(model_id)
            else:
                self._models[model_id] = model
                self.load_count += 1
                if len(self._models) > self.max_num_models:
                    evicted.append(self._models.popitem(last=False)[1])
                    self.evict_count += 1
        if self.unload_fn is not None:
            for m in evicted:
                self.unload_fn(m)
            if duplicate is not None:
                self.unload_fn(duplicate)
        return model

    def loaded_model_ids(self):
        with self._lock:
            return list(self._models)

    def __call__(self, model_id: str, request: Any,
                 handler: Callable[[Any, Any], Any]) -> Any:
        model = self.get_model(model_id)
        prev = getattr(_current_model_id, "value", None)
        _current_model_id.value = model_id
        try:
            return handler(model, request)
        finally:
            _current_model_id.value = prev


def multiplexed(*, max_num_models_per_replica: int = 3,
                unload_fn: Optional[Callable[[Any], None]] = None):
    """Decorator for a model-loader method; the wrapped loader becomes an
    LRU-cached ``loader(model_id) -> model``:

        class Replica:
            @multiplexed(max_num_models_per_replica=4)
            def get_model(self, model_id: str):
                return load_lora(model_id)

            def predict(self, model_id, x):
                return self.get_model(model_id)(x)
    """

    def decorate(fn: Callable):
        attr = f"__multiplexer_{fn.__name__}"

        def wrapper(self, model_id: str):
            mux = getattr(self, attr, None)
            if mux is None:
                mux = Multiplexer(lambda mid: fn(self, mid),
                                  max_num_models_per_replica, unload_fn)
                setattr(self, attr, mux)
            return mux.get_model(model_id)

        wrapper.__name__ = fn.__name__
        return wrapper

    return decorate
