"""@serve.batch: coalesce concurrent calls into one batched invocation.

Reference analog: python/ray/serve/batching.py (_BatchQueue + @serve.batch).
Essential on TPU: the MXU wants one [B, ...] matmul, not B sequential
[1, ...] calls, so the replica accumulates requests for up to
``batch_wait_timeout_s`` (or until ``max_batch_size``) and runs the
underlying function once on the list.

Works on methods and free functions. The wrapped callable must accept a
LIST of requests and return a LIST of responses of the same length.

    class Model:
        @serve_batch(max_batch_size=8, batch_wait_timeout_s=0.01)
        def predict(self, inputs: List[np.ndarray]) -> List[np.ndarray]:
            return list(model(np.stack(inputs)))

Each individual call `model.predict(x)` (threaded, e.g. one per in-flight
request in a replica with max_concurrency > 1) returns its own single
response.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Any, Callable, List, Optional

__all__ = ["serve_batch"]


class _Pending:
    __slots__ = ("value", "event", "result", "error")

    def __init__(self, value):
        self.value = value
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None


class _BatchQueue:
    def __init__(self, fn: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.batch_wait_timeout_s = batch_wait_timeout_s
        self._lock = threading.Lock()
        self._queue: List[_Pending] = []
        self._leader_running = False

    def submit(self, instance, value) -> Any:
        item = _Pending(value)
        lead = False
        with self._lock:
            self._queue.append(item)
            if not self._leader_running:
                self._leader_running = True
                lead = True
        if lead:
            self._run_batches(instance)
        item.event.wait()
        if item.error is not None:
            raise item.error
        return item.result

    def _run_batches(self, instance):
        """The first caller becomes the batch leader: it waits out the batch
        window, drains the queue, and executes; followers just block on
        their event. Repeats while more requests arrived during execution."""
        while True:
            deadline = time.monotonic() + self.batch_wait_timeout_s
            while time.monotonic() < deadline:
                with self._lock:
                    if len(self._queue) >= self.max_batch_size:
                        break
                time.sleep(min(0.001, self.batch_wait_timeout_s / 4 or 0.001))
            with self._lock:
                batch = self._queue[:self.max_batch_size]
                self._queue = self._queue[self.max_batch_size:]
                if not batch:
                    self._leader_running = False
                    return
            try:
                args = ([p.value for p in batch],)
                results = (self.fn(instance, *args) if instance is not None
                           else self.fn(*args))
                if len(results) != len(batch):
                    raise ValueError(
                        f"batched function returned {len(results)} results "
                        f"for {len(batch)} requests")
                for p, r in zip(batch, results):
                    p.result = r
                    p.event.set()
            except BaseException as e:  # noqa: BLE001 — propagate to callers
                for p in batch:
                    p.error = e
                    p.event.set()
            with self._lock:
                if not self._queue:
                    self._leader_running = False
                    return


def serve_batch(_fn: Optional[Callable] = None, *, max_batch_size: int = 8,
                batch_wait_timeout_s: float = 0.01):
    """Decorator; use bare or with arguments."""

    def decorate(fn: Callable):
        queue = _BatchQueue(fn, max_batch_size, batch_wait_timeout_s)

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if kwargs:
                raise TypeError("@serve_batch calls must be positional")
            if len(args) == 2:       # bound method: (self, value)
                return queue.submit(args[0], args[1])
            if len(args) == 1:       # free function: (value,)
                return queue.submit(None, args[0])
            raise TypeError("@serve_batch functions take exactly one request")

        wrapper._batch_queue = queue
        return wrapper

    if _fn is not None:
        return decorate(_fn)
    return decorate
