"""DeploymentHandle: request routing to replicas.

Reference analog: python/ray/serve/handle.py:625 DeploymentHandle +
router.py:578/pow_2_scheduler.py:52 (power-of-two-choices on queue length).
Client-side: the handle tracks its own in-flight count per replica and picks
the lighter of two random replicas — the same load-balancing rule without a
probe round-trip.
"""

from __future__ import annotations

import random
import time
from typing import Any, Dict, List, Optional

import ray_tpu


class DeploymentResponse:
    """Future-like wrapper over the underlying ObjectRef."""

    def __init__(self, ref, on_done):
        self._ref = ref
        self._on_done = on_done
        self._done = False

    def result(self, timeout: Optional[float] = 60.0,
               timeout_s: Optional[float] = None):
        """`timeout_s` is the reference's spelling (DeploymentResponse
        .result(timeout_s=...)); both names are accepted."""
        if timeout_s is not None:
            timeout = timeout_s
        try:
            return ray_tpu.get(self._ref, timeout=timeout)
        finally:
            if not self._done:
                self._done = True
                self._on_done()


class _StreamingResponse:
    """Iterator over a streaming call's item refs; keeps the handle's
    power-of-two load accounting honest for long-lived streams."""

    def __init__(self, gen, on_done):
        self._gen = gen
        self._on_done = on_done
        self._done = False

    def _finish(self):
        if not self._done:
            self._done = True
            self._on_done()

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self._gen)
        except BaseException:
            self._finish()
            raise

    def next(self, timeout=None):
        try:
            return self._gen.next(timeout)
        except TimeoutError:
            raise  # transient poll timeout: the stream is still live
        except BaseException:
            self._finish()
            raise

    def __del__(self):
        self._finish()


class DeploymentHandle:
    @property
    def REFRESH_INTERVAL_S(self):
        from ray_tpu.config import cfg

        return cfg().serve_handle_refresh_s

    def __init__(self, deployment_name: str, method_name: str = "__call__"):
        self.deployment_name = deployment_name
        self.method_name = method_name
        self._replicas: List = []
        self._version = -1
        self._inflight: Dict[int, int] = {}
        self._last_refresh = 0.0

    def options(self, method_name: str) -> "DeploymentHandle":
        h = DeploymentHandle(self.deployment_name, method_name)
        h._replicas = self._replicas
        h._version = self._version
        h._inflight = self._inflight
        return h

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        return self.options(item)

    def _refresh(self):
        from ray_tpu.serve.api import _get_controller

        controller = _get_controller()
        info = ray_tpu.get(controller.get_replicas.remote(self.deployment_name),
                           timeout=60)
        if not info["found"]:
            # Acknowledge the deletion push so _needs_refresh doesn't pay
            # a blocking controller round-trip on EVERY subsequent call of
            # a surviving handle.
            from ray_tpu.serve.config_watcher import ConfigWatcher

            pushed = ConfigWatcher.get().version(self.deployment_name)
            if pushed is not None:
                self._version = pushed
            self._last_refresh = time.monotonic()
            raise ValueError(f"no deployment named {self.deployment_name!r}")
        if info["version"] != self._version:
            self._replicas = info["replicas"]
            self._version = info["version"]
            self._inflight = {i: 0 for i in range(len(self._replicas))}
        self._last_refresh = time.monotonic()

    def _needs_refresh(self) -> bool:
        """Push-first (LongPollHost analog): the shared ConfigWatcher gets
        controller pushes on every deploy/scale/delete, so a handle only
        talks to the controller when its routing set is actually stale.
        Time-based refresh remains ONLY as the degraded mode (watcher
        not yet started / subscription down / no event seen yet)."""
        if not self._replicas:
            return True
        from ray_tpu.serve.config_watcher import ConfigWatcher

        watcher = ConfigWatcher.get()
        watcher.ensure_started()
        pushed = watcher.version(self.deployment_name)
        if pushed is not None and watcher.healthy:
            return pushed != self._version
        return (time.monotonic() - self._last_refresh
                > self.REFRESH_INTERVAL_S)

    def replica_handles(self) -> List:
        """The current replica actor handles (refresh-if-stale). For
        routers that do their own replica selection (llm/router.py) instead
        of this handle's blind power-of-two: call
        `replica.handle_request.remote(method, args, kwargs)` directly."""
        if self._needs_refresh():
            try:
                self._refresh()
            except Exception:
                if not self._replicas:
                    raise
        return list(self._replicas)

    def _pick_replica(self) -> int:
        n = len(self._replicas)
        if n == 0:
            raise RuntimeError(
                f"deployment {self.deployment_name!r} has no replicas")
        if n == 1:
            return 0
        a, b = random.sample(range(n), 2)
        return a if self._inflight.get(a, 0) <= self._inflight.get(b, 0) else b

    def remote_stream(self, *args, **kwargs):
        """Streaming call: the replica method must return a generator; items
        stream back as they are yielded (ObjectRefGenerator of item refs).
        Reference analog: serve streaming responses over
        ReportGeneratorItemReturns (core_worker.proto:462)."""
        if self._needs_refresh():
            try:
                self._refresh()
            except Exception:
                if not self._replicas:
                    raise
        idx = self._pick_replica()
        replica = self._replicas[idx]
        self._inflight[idx] = self._inflight.get(idx, 0) + 1
        gen = replica.handle_request.options(
            num_returns="streaming").remote(self.method_name, args, kwargs)
        return _StreamingResponse(gen, lambda i=idx: self._on_stream_done(i))

    def _on_stream_done(self, idx: int):
        self._inflight[idx] = max(0, self._inflight.get(idx, 0) - 1)

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        # Push-triggered refresh (controller pubsub via ConfigWatcher);
        # time-based re-poll only as the degraded fallback.
        if self._needs_refresh():
            try:
                self._refresh()
            except Exception:
                if not self._replicas:
                    raise
        idx = self._pick_replica()
        replica = self._replicas[idx]
        self._inflight[idx] = self._inflight.get(idx, 0) + 1
        from ray_tpu.runtime import metric_defs

        metric_defs.SERVE_REQUESTS.inc(
            tags={"deployment": self.deployment_name})
        ref = replica.handle_request.remote(self.method_name, args, kwargs)

        def on_done(i=idx):
            self._inflight[i] = max(0, self._inflight.get(i, 0) - 1)

        return DeploymentResponse(ref, on_done)
