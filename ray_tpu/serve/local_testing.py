"""Local testing mode: run deployments in-process, no cluster.

Reference analog: python/ray/serve/_private/local_testing_mode.py:1 —
`serve.run(app, local_testing_mode=True)` executes user callables directly
in the driver process so deployment logic (composition, method routing,
streaming, errors) is unit-testable with zero actors, zero RPC, and
sub-second startup. The handle surface mirrors DeploymentHandle:
`.remote(...)` -> response with `.result(timeout=...)`,
`.remote_stream(...)` -> iterator, `.options(method)` / attribute access
for method routing.
"""

from __future__ import annotations

import inspect
import queue
import threading
from typing import Any, Dict, List, Optional

_local_deployments: Dict[str, "_LocalReplica"] = {}


class LocalDeploymentResponse:
    """Synchronous DeploymentResponse stand-in. The call runs on a worker
    thread so `.result(timeout=...)` has real timeout semantics (a hung
    user callable fails the test instead of wedging it)."""

    def __init__(self, fn, args, kwargs):
        self._q: "queue.Queue" = queue.Queue(maxsize=1)

        def run():
            try:
                out = fn(*args, **kwargs)
                if inspect.iscoroutine(out):
                    import asyncio

                    out = asyncio.run(out)
                self._q.put((True, out))
            except BaseException as e:  # delivered to .result()
                self._q.put((False, e))

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="serve-local-call")
        self._thread.start()

    def result(self, timeout: Optional[float] = 60.0,
               timeout_s: Optional[float] = None):
        if timeout_s is not None:
            timeout = timeout_s
        # Cache the outcome: result() must be repeatable (the real
        # DeploymentResponse allows any number of result() calls).
        if not hasattr(self, "_outcome"):
            try:
                self._outcome = self._q.get(timeout=timeout)
            except queue.Empty:
                raise TimeoutError("local deployment call timed out")
        ok, value = self._outcome
        if not ok:
            raise value
        return value


class _LocalReplica:
    def __init__(self, name: str, func_or_class, init_args, init_kwargs):
        self.name = name
        if inspect.isclass(func_or_class):
            self.callable = func_or_class(*init_args, **init_kwargs)
        else:
            self.callable = func_or_class

    def method(self, method_name: str):
        if method_name == "__call__":
            if callable(self.callable):
                return self.callable
            raise AttributeError(
                f"deployment {self.name!r} object is not callable")
        return getattr(self.callable, method_name)


class LocalDeploymentHandle:
    """In-process DeploymentHandle twin (same call surface)."""

    def __init__(self, deployment_name: str, method_name: str = "__call__"):
        self.deployment_name = deployment_name
        self.method_name = method_name

    def options(self, method_name: str) -> "LocalDeploymentHandle":
        return LocalDeploymentHandle(self.deployment_name, method_name)

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        return self.options(item)

    def _target(self):
        replica = _local_deployments.get(self.deployment_name)
        if replica is None:
            raise ValueError(
                f"no local deployment named {self.deployment_name!r}")
        return replica.method(self.method_name)

    def remote(self, *args, **kwargs) -> LocalDeploymentResponse:
        return LocalDeploymentResponse(self._target(), args, kwargs)

    def remote_stream(self, *args, **kwargs):
        gen = self._target()(*args, **kwargs)
        if not inspect.isgenerator(gen):
            raise TypeError(
                f"{self.deployment_name}.{self.method_name} did not return "
                "a generator (required for remote_stream)")
        return gen


def run_local(deployments: List) -> LocalDeploymentHandle:
    """serve.run(..., local_testing_mode=True) implementation: deploy each
    Deployment in-process, resolving bound child Deployments to local
    handles (same composition semantics as the real path)."""
    from ray_tpu.serve.deployment import Deployment

    deployed: set = set()

    def resolve(obj):
        if isinstance(obj, Deployment):
            deploy(obj)
            return LocalDeploymentHandle(obj.name)
        if isinstance(obj, (list, tuple)):
            return type(obj)(resolve(x) for x in obj)
        if isinstance(obj, dict):
            return {k: resolve(v) for k, v in obj.items()}
        return obj

    def deploy(dep):
        if dep.name in deployed:
            return
        deployed.add(dep.name)
        init_args = tuple(resolve(a) for a in dep.init_args)
        init_kwargs = {k: resolve(v) for k, v in dep.init_kwargs.items()}
        _local_deployments[dep.name] = _LocalReplica(
            dep.name, dep.func_or_class, init_args, init_kwargs)

    for dep in deployments:
        deploy(dep)
    return LocalDeploymentHandle(deployments[0].name)


def get_local_handle(name: str) -> Optional[LocalDeploymentHandle]:
    return (LocalDeploymentHandle(name)
            if name in _local_deployments else None)


def local_status() -> List[dict]:
    return [{"name": n, "num_replicas": 1, "status": "RUNNING",
             "local_testing_mode": True} for n in _local_deployments]


def delete_local(name: str) -> bool:
    return _local_deployments.pop(name, None) is not None


def shutdown_local() -> None:
    _local_deployments.clear()
