"""Communicator interface.

Reference analog: python/ray/experimental/channel/communicator.py:19
(Communicator ABC used by compiled-graph channels and util.collective).
The TPU-native design splits collectives into two planes:

  * in-graph: `jax.lax` collectives (psum/all_gather/ppermute/all_to_all)
    compiled into XLA programs over the device mesh — the ICI data plane.
    These don't go through this interface; they ARE the program.
  * out-of-graph: control-plane collectives over host arrays (rendezvous,
    gradient sync for CPU tests, cross-slice DCN fallback). This interface
    covers those, with a TCP implementation (CPU/gloo analog) and a JAX
    implementation that stages through device meshes when available.
"""

from __future__ import annotations

import abc
from typing import List, Sequence

import numpy as np

REDUCE_OPS = ("sum", "prod", "min", "max", "mean")


class Communicator(abc.ABC):
    """A process group: `world_size` ranks that communicate collectively."""

    def __init__(self, rank: int, world_size: int, group_name: str):
        assert 0 <= rank < world_size
        self.rank = rank
        self.world_size = world_size
        self.group_name = group_name

    @abc.abstractmethod
    def allreduce(self, array: np.ndarray, op: str = "sum") -> np.ndarray:
        ...

    @abc.abstractmethod
    def allgather(self, array: np.ndarray) -> List[np.ndarray]:
        ...

    @abc.abstractmethod
    def reducescatter(self, arrays: Sequence[np.ndarray], op: str = "sum") -> np.ndarray:
        ...

    @abc.abstractmethod
    def broadcast(self, array: np.ndarray, src_rank: int = 0) -> np.ndarray:
        ...

    @abc.abstractmethod
    def send(self, array: np.ndarray, dst_rank: int) -> None:
        ...

    @abc.abstractmethod
    def recv(self, shape, dtype, src_rank: int) -> np.ndarray:
        ...

    @abc.abstractmethod
    def barrier(self) -> None:
        ...

    def alltoall(self, arrays: Sequence[np.ndarray]) -> List[np.ndarray]:
        """Default all-to-all via send/recv pairs (override for better)."""
        out: List[np.ndarray] = [None] * self.world_size  # type: ignore
        out[self.rank] = np.asarray(arrays[self.rank])
        for offset in range(1, self.world_size):
            dst = (self.rank + offset) % self.world_size
            src = (self.rank - offset) % self.world_size
            if self.rank % 2 == 0:
                self.send(np.asarray(arrays[dst]), dst)
                out[src] = self.recv(None, None, src)
            else:
                out[src] = self.recv(None, None, src)
                self.send(np.asarray(arrays[dst]), dst)
        return out

    def close(self) -> None:
        pass


def reduce_arrays(arrays: Sequence[np.ndarray], op: str) -> np.ndarray:
    stack = np.stack([np.asarray(a) for a in arrays])
    if op == "sum":
        return stack.sum(axis=0)
    if op == "prod":
        return stack.prod(axis=0)
    if op == "min":
        return stack.min(axis=0)
    if op == "max":
        return stack.max(axis=0)
    if op == "mean":
        return stack.mean(axis=0)
    raise ValueError(f"unknown reduce op {op!r}")
