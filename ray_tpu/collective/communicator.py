"""Communicator interface.

Reference analog: python/ray/experimental/channel/communicator.py:19
(Communicator ABC used by compiled-graph channels and util.collective).
The TPU-native design splits collectives into two planes:

  * in-graph: `jax.lax` collectives (psum/all_gather/ppermute/all_to_all)
    compiled into XLA programs over the device mesh — the ICI data plane.
    These don't go through this interface; they ARE the program.
  * out-of-graph: control-plane collectives over host arrays (rendezvous,
    gradient sync for CPU tests, cross-slice DCN fallback). This interface
    covers those, with a TCP implementation (CPU/gloo analog) and a JAX
    implementation that stages through device meshes when available.
"""

from __future__ import annotations

import abc
import contextlib
import logging
import threading
import time
from typing import Callable, List, Optional, Sequence

import numpy as np

from ray_tpu.core.exceptions import CollectiveAbortError

logger = logging.getLogger(__name__)

REDUCE_OPS = ("sum", "prod", "min", "max", "mean")


def abort_key(group_name: str) -> str:
    """GCS KV key of a group's abort flag (non-empty value = abort reason).
    Settable by ANY process — the Train controller uses it to unblock a
    worker group's in-flight collectives during a gang restart."""
    return f"collective:{group_name}:abort"


def heartbeat_key(group_name: str, rank: int) -> str:
    return f"collective:{group_name}:hb:{rank}"


class Work:
    """Handle for an asynchronously launched collective op.

    Reference analog: torch.distributed's Work / NCCL's stream events. Ops
    submitted through a communicator's `*_async` methods complete in FIFO
    submission order (one op thread per group drains them); `op_id` is the
    per-group sequence number, identical across ranks when every rank
    submits the same op stream — the invariant bucketed gradient reduction
    relies on. Errors (including CollectiveAbortError from a watchdog
    abort) surface at `wait()`, never silently."""

    __slots__ = ("op_id", "group_name", "rank", "world_size", "_done",
                 "_result", "_error")

    def __init__(self, op_id: int, group_name: str,
                 rank: Optional[int] = None,
                 world_size: Optional[int] = None):
        self.op_id = op_id
        self.group_name = group_name
        self.rank = rank
        self.world_size = world_size
        self._done = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None

    def _finish(self, result=None, error: Optional[BaseException] = None):
        self._result = result
        self._error = error
        self._done.set()

    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None):
        """Block until the op completes; return its result or re-raise its
        error. The executing op observes the group's abort flag and per-op
        deadline itself, so an aborted group completes this (exceptionally)
        within one watchdog tick."""
        if not self._done.is_set():
            # The caller is about to park: make the hang diagnosable
            # (stack dumps / wait-graph name the group + op id).
            from ray_tpu.core import blocked as blocked_mod

            with blocked_mod.blocked_on(
                    blocked_mod.COLLECTIVE_OP, group=self.group_name,
                    op_id=self.op_id, rank=self.rank,
                    world_size=self.world_size):
                if not self._done.wait(timeout):
                    raise TimeoutError(
                        f"collective op {self.op_id} on group "
                        f"{self.group_name!r} not done after {timeout}s")
        if self._error is not None:
            raise self._error
        return self._result


class Communicator(abc.ABC):
    """A process group: `world_size` ranks that communicate collectively."""

    def __init__(self, rank: int, world_size: int, group_name: str):
        assert 0 <= rank < world_size
        self.rank = rank
        self.world_size = world_size
        self.group_name = group_name
        # Abort plumbing: the flag is checked at op entry and, for blocking
        # backends, from inside receive loops (poll-timeout ticks), so a
        # set flag surfaces CollectiveAbortError within one watchdog
        # interval instead of the socket timeout.
        self._abort_event = threading.Event()
        self._abort_reason = ""
        self._watchdog: Optional["CollectiveWatchdog"] = None
        self._active_ops = 0
        self._op_lock = threading.Lock()
        # Sequence number of the op the op thread is currently executing
        # (backends with a real op thread keep it current); blocked-on
        # records use it to name which op a stuck rank is inside.
        self._current_op_id = 0

    # ---- abort -----------------------------------------------------------

    def abort(self, reason: str = "aborted") -> None:
        """Set the group's abort flag locally: every blocking op in flight
        (and every future op) raises CollectiveAbortError. Subclasses with
        a KV rendezvous also propagate to peers (see TCPCommunicator)."""
        if not self._abort_event.is_set():
            self._abort_reason = reason or "aborted"
            self._abort_event.set()

    @property
    def aborted(self) -> bool:
        return self._abort_event.is_set()

    def check_abort(self) -> None:
        if self._abort_event.is_set():
            raise CollectiveAbortError(self.group_name, self._abort_reason)

    @property
    def op_active(self) -> bool:
        return self._active_ops > 0

    @contextlib.contextmanager
    def _op(self):
        """Blocking-op guard: the watchdog only applies peer-liveness
        staleness checks while an op is actually in flight (an idle group
        whose peers exited cleanly must not abort retroactively)."""
        self.check_abort()
        with self._op_lock:
            self._active_ops += 1
        from ray_tpu.core import blocked as blocked_mod

        try:
            with blocked_mod.blocked_on(
                    blocked_mod.COLLECTIVE_OP, group=self.group_name,
                    op_id=self._current_op_id, rank=self.rank,
                    world_size=self.world_size):
                yield
        finally:
            with self._op_lock:
                self._active_ops -= 1

    @abc.abstractmethod
    def allreduce(self, array: np.ndarray, op: str = "sum") -> np.ndarray:
        ...

    @abc.abstractmethod
    def allgather(self, array: np.ndarray) -> List[np.ndarray]:
        ...

    @abc.abstractmethod
    def reducescatter(self, arrays: Sequence[np.ndarray], op: str = "sum") -> np.ndarray:
        ...

    @abc.abstractmethod
    def broadcast(self, array: np.ndarray, src_rank: int = 0) -> np.ndarray:
        ...

    @abc.abstractmethod
    def send(self, array: np.ndarray, dst_rank: int) -> None:
        ...

    @abc.abstractmethod
    def recv(self, shape, dtype, src_rank: int) -> np.ndarray:
        ...

    @abc.abstractmethod
    def barrier(self) -> None:
        ...

    # ---- async handles ---------------------------------------------------
    # Default: run synchronously and hand back a completed Work, so every
    # backend supports the async API; backends with a real op thread (the
    # TCP ring transport) override these to actually overlap.

    def _completed_work(self, fn) -> Work:
        work = Work(0, self.group_name)
        try:
            work._finish(result=fn())
        except BaseException as e:
            work._finish(error=e)
        return work

    def allreduce_async(self, array: np.ndarray, op: str = "sum") -> Work:
        return self._completed_work(lambda: self.allreduce(array, op))

    def allgather_async(self, array: np.ndarray) -> Work:
        return self._completed_work(lambda: self.allgather(array))

    def reducescatter_async(self, arrays: Sequence[np.ndarray],
                            op: str = "sum") -> Work:
        return self._completed_work(lambda: self.reducescatter(arrays, op))

    def broadcast_async(self, array: np.ndarray, src_rank: int = 0) -> Work:
        return self._completed_work(lambda: self.broadcast(array, src_rank))

    def alltoall(self, arrays: Sequence[np.ndarray]) -> List[np.ndarray]:
        """Default all-to-all via send/recv pairs (override for better)."""
        out: List[np.ndarray] = [None] * self.world_size  # type: ignore
        out[self.rank] = np.asarray(arrays[self.rank])
        for offset in range(1, self.world_size):
            dst = (self.rank + offset) % self.world_size
            src = (self.rank - offset) % self.world_size
            if self.rank % 2 == 0:
                self.send(np.asarray(arrays[dst]), dst)
                out[src] = self.recv(None, None, src)
            else:
                out[src] = self.recv(None, None, src)
                self.send(np.asarray(arrays[dst]), dst)
        return out

    def close(self) -> None:
        pass


class CollectiveWatchdog:
    """Peer-liveness + abort propagation for out-of-graph collectives.

    One thread per communicator. Each tick (cfg().collective_watchdog_
    interval_s) it:

      * bumps this rank's heartbeat counter in the rendezvous KV,
      * reads the group's abort key — any non-empty value aborts the local
        communicator (this is how a remote `abort_collective_group` or the
        Train controller's gang-restart reaches a rank blocked in recv),
      * while a blocking op is in flight, checks every peer's heartbeat
        counter: a counter unchanged for `collective_peer_miss_threshold`
        consecutive ticks means the peer's process is gone (its watchdog
        died with it) — the group aborts in seconds instead of hanging to
        the 120 s socket timeout.

    KV failures are tolerated (the GCS may be briefly down); the watchdog
    just retries next tick.
    """

    def __init__(self, comm: Communicator,
                 kv_put: Callable[[str, str], None],
                 kv_get: Callable[[str], Optional[str]],
                 interval_s: Optional[float] = None,
                 miss_threshold: Optional[int] = None):
        from ray_tpu.config import cfg

        self.comm = comm
        self._kv_put = kv_put
        self._kv_get = kv_get
        self.interval_s = (interval_s if interval_s is not None
                           else cfg().collective_watchdog_interval_s)
        self.miss_threshold = (miss_threshold if miss_threshold is not None
                               else cfg().collective_peer_miss_threshold)
        self._beats = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # peer rank -> (last seen counter value, consecutive stale ticks)
        self._peer_state: dict = {}

    def start(self) -> "CollectiveWatchdog":
        # First beat synchronously: peers must be able to see us from the
        # moment the group exists, or a slow-to-start watchdog thread would
        # read as a dead peer.
        self._beat()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"collective-watchdog-{self.comm.group_name}-"
                 f"{self.comm.rank}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def join(self, timeout: float = 5.0) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    def _beat(self):
        self._beats += 1
        try:
            self._kv_put(heartbeat_key(self.comm.group_name, self.comm.rank),
                         str(self._beats))
        except Exception:
            pass

    def _run(self):
        while not self._stop.wait(self.interval_s):
            if self.comm.aborted:
                return
            self._beat()
            try:
                self._check_abort_key()
                if self.comm.op_active:
                    self._check_peers()
            except Exception:
                logger.debug("collective watchdog tick failed", exc_info=True)
            if self.comm.aborted:
                return

    def _check_abort_key(self):
        try:
            value = self._kv_get(abort_key(self.comm.group_name))
        except Exception:
            return
        if value:
            self.comm.abort(value)

    def _check_peers(self):
        for peer in range(self.comm.world_size):
            if peer == self.comm.rank:
                continue
            try:
                value = self._kv_get(heartbeat_key(self.comm.group_name, peer))
            except Exception:
                return
            if value is None:
                continue  # peer not through rendezvous yet
            last, stale = self._peer_state.get(peer, (None, 0))
            stale = stale + 1 if value == last else 0
            self._peer_state[peer] = (value, stale)
            if stale >= self.miss_threshold:
                self.comm.abort(
                    f"peer rank {peer} lost (no watchdog heartbeat for "
                    f"{stale} x {self.interval_s:g}s)")
                return


def reduce_arrays(arrays: Sequence[np.ndarray], op: str) -> np.ndarray:
    stack = np.stack([np.asarray(a) for a in arrays])
    if op == "sum":
        return stack.sum(axis=0)
    if op == "prod":
        return stack.prod(axis=0)
    if op == "min":
        return stack.min(axis=0)
    if op == "max":
        return stack.max(axis=0)
    if op == "mean":
        return stack.mean(axis=0)
    raise ValueError(f"unknown reduce op {op!r}")
