"""TCP collective backend: chunked ring data plane over per-rank p2p links.

Reference analog: python/ray/util/collective/collective_group/
gloo_collective_group.py:184 GLOOGroup, rebuilt around bandwidth-optimal
ring algorithms (the structure the ring-allreduce scheduling literature
targets). Two planes coexist:

  * ring (default): allreduce = ring reduce-scatter + ring all-gather;
    allgather / reducescatter / broadcast ride the same neighbor links.
    Large tensors move as fixed-size chunks (``collective_chunk_bytes``)
    so transfers pipeline across hops and per-op scratch memory stays
    bounded at one chunk. Array bodies cross the wire as RAW-BUFFER frames
    (a 97-byte binary header carries dtype/shape/offset; the body is the
    ndarray buffer) — zero pickling on the steady-state path, provable via
    ray_tpu.core.serialization's counters, which this transport bumps:
    ``fast_ndarray``/``deserialize_fast`` per raw frame, ``pickle``/
    ``deserialize_pickle`` per control frame.
  * hub (legacy star, ``topology="hub"``): rank 0 gathers pickled
    payloads, reduces, scatters. Kept for barriers, exotic dtypes, and as
    the microbenchmark baseline the ring is measured against.

Every op runs on a per-group op thread in FIFO submission order, which is
what makes the async handles (`allreduce_async(...) -> Work`) safe: ranks
submitting the same op stream execute it in the same order over the same
sockets. Chunk sends run on a separate tx thread so a rank can sink its
outgoing chunk while blocked receiving the incoming one — full-duplex
neighbor links with no ring-wide send deadlock regardless of chunk size.

Abort semantics (PR 1) are preserved per chunk: every socket tick observes
the group abort flag and the per-op deadline, and a mid-ring peer failure
(EOF / reset) aborts the group with propagation, so every rank unblocks
with CollectiveAbortError within ~one watchdog interval.
"""

from __future__ import annotations

import pickle
import queue
import socket
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ray_tpu.core import serialization as _ser
from ray_tpu.core.exceptions import CollectiveAbortError
from ray_tpu.collective.communicator import (
    Communicator, CollectiveWatchdog, Work, abort_key, reduce_arrays)

# Wire: [u64 body length][u8 frame kind][body]
_HDR = struct.Struct("<QB")
_K_CTRL = 0    # body = pickled control object (rendezvous, hub plane)
_K_ARRAY = 1   # body = _AMETA header + raw ndarray chunk bytes

# Array-chunk header: dtype str (NUL-padded), ndim, 8 dims of the FULL
# array this chunk belongs to, element offset of the chunk, chunk elements.
_AMETA = struct.Struct("<16sB8QQQ")
_MAX_DIMS = 8


# ---------------------------------------------------------------------------
# Low-level socket IO: every tick observes the abort check + op deadline.
# ---------------------------------------------------------------------------


def _sock_send(sock: socket.socket, view: memoryview,
               check: Optional[Callable] = None,
               deadline: Optional[float] = None) -> None:
    if check is None and deadline is None:
        sock.sendall(view)
        return
    # Poll-timeout sockets: a partial send to a slow peer must not surface
    # as a spurious socket.timeout — retry each tick, observing abort flag
    # and per-op deadline just like the receive side.
    while view.nbytes:
        try:
            sent = sock.send(view)
        except socket.timeout:
            if check is not None:
                check()
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("collective op deadline exceeded")
            continue
        except OSError:
            if check is not None:
                check()
            raise
        view = view[sent:]


def _sock_recv_into(sock: socket.socket, view: memoryview,
                    check: Optional[Callable] = None,
                    deadline: Optional[float] = None) -> None:
    got, total = 0, view.nbytes
    while got < total:
        try:
            n = sock.recv_into(view[got:], min(1 << 20, total - got))
        except socket.timeout:
            if check is None and deadline is None:
                raise  # legacy blocking behavior (rendezvous paths)
            if check is not None:
                check()
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("collective op deadline exceeded")
            continue
        except OSError:
            # close() sets the abort flag then closes sockets; the abort is
            # the real story, not the EBADF it causes.
            if check is not None:
                check()
            raise
        if n == 0:
            if check is not None:
                check()
            raise ConnectionError("collective peer disconnected")
        got += n


def _read_hdr(sock, check, deadline) -> Tuple[int, int]:
    hdr = bytearray(_HDR.size)
    _sock_recv_into(sock, memoryview(hdr), check, deadline)
    length, kind = _HDR.unpack(hdr)
    return length, kind


def _read_ameta(sock, check, deadline):
    """Read + parse one _AMETA array-chunk header; returns
    (dtype, full_shape, offset_elems, chunk_elems)."""
    raw = bytearray(_AMETA.size)
    _sock_recv_into(sock, memoryview(raw), check, deadline)
    fields = _AMETA.unpack(raw)
    dtype = np.dtype(fields[0].rstrip(b"\x00").decode())
    ndim = fields[1]
    shape = tuple(fields[2:2 + ndim])
    offset, nelems = fields[10], fields[11]
    return dtype, shape, offset, nelems


def _frame_views(chunk: np.ndarray, full_shape=None, offset: int = 0) -> List:
    """Build the wire views for one raw array chunk: [header+meta, payload].

    The payload view aliases the caller's buffer — zero copies on the send
    path. `full_shape` is the shape of the array the chunk belongs to
    (defaults to the chunk's own shape for standalone frames)."""
    shape = tuple(full_shape) if full_shape is not None else tuple(chunk.shape)
    if len(shape) > _MAX_DIMS:
        raise ValueError(f"array rank {len(shape)} exceeds wire max {_MAX_DIMS}")
    dims = list(shape) + [0] * (_MAX_DIMS - len(shape))
    meta = _AMETA.pack(chunk.dtype.str.encode().ljust(16, b"\x00"),
                       len(shape), *dims, offset, chunk.size)
    payload = memoryview(chunk).cast("B")
    head = _HDR.pack(_AMETA.size + payload.nbytes, _K_ARRAY) + meta
    _ser.counters["fast_ndarray"] += 1
    return [memoryview(head), payload]


def _ctrl_views(obj) -> List:
    # Array payloads ride raw _K_ARRAY frames (counter-proven by
    # tests/test_collective.py::test_ring_zero_pickle_steady_state).
    # graftlint: allow[hot-pickle] control frames only (rank ids, op tags)
    body = pickle.dumps(obj, protocol=5)
    _ser.counters["pickle"] += 1
    return [memoryview(_HDR.pack(len(body), _K_CTRL) + body)]


def _send_msg(sock: socket.socket, obj, check: Optional[Callable] = None,
              deadline: Optional[float] = None) -> None:
    """Send one control frame (pickled body). Array bodies never go through
    here on the ring path — they ride raw frames via _frame_views."""
    for view in _ctrl_views(obj):
        _sock_send(sock, view, check, deadline)


def _recv_msg(sock: socket.socket, check: Optional[Callable] = None,
              deadline: Optional[float] = None):
    """Receive one logical message: a pickled control object, or a raw
    array (reassembled across its chunk frames, received straight into the
    destination buffer). With `check`/`deadline` set (and the socket on a
    short poll timeout), each timeout tick runs `check()` — which raises
    CollectiveAbortError once the group's abort flag is set — and enforces
    the per-op deadline, so a blocked receive unblocks within one poll tick
    of an abort instead of the full socket timeout."""
    length, kind = _read_hdr(sock, check, deadline)
    if kind == _K_CTRL:
        body = bytearray(length)
        _sock_recv_into(sock, memoryview(body), check, deadline)
        _ser.counters["deserialize_pickle"] += 1
        # Steady-state ring traffic is all raw frames (_frame_views).
        # graftlint: allow[hot-pickle] _K_CTRL branch only
        return pickle.loads(bytes(body))
    if kind != _K_ARRAY:
        raise RuntimeError(f"collective protocol error: unknown frame kind {kind}")
    dtype, shape, offset, nelems = _read_ameta(sock, check, deadline)
    out = np.empty(shape, dtype)
    flat = out.reshape(-1)
    total = flat.size
    got = 0
    while True:
        if nelems:
            _sock_recv_into(sock, memoryview(flat[offset:offset + nelems]).cast("B"),
                            check, deadline)
            got += nelems
        _ser.counters["deserialize_fast"] += 1
        if got >= total:
            return out
        length, kind = _read_hdr(sock, check, deadline)
        if kind != _K_ARRAY:
            raise RuntimeError("collective protocol error: truncated array stream")
        _, _, offset, nelems = _read_ameta(sock, check, deadline)


# ---------------------------------------------------------------------------
# Reduction helpers.
# ---------------------------------------------------------------------------

_REDUCE_INPLACE = {
    "sum": np.add, "prod": np.multiply, "min": np.minimum, "max": np.maximum,
}


def _reduce_into(dst: np.ndarray, src: np.ndarray, op: str) -> None:
    _REDUCE_INPLACE[op](dst, src, out=dst)


def _mean_div(flat: np.ndarray, world_size: int) -> np.ndarray:
    if np.issubdtype(flat.dtype, np.inexact):
        flat /= world_size
        return flat
    # Integer mean mirrors the hub's np.stack(...).mean: float64 result.
    return flat / world_size


def _ring_wire_ok(arr: np.ndarray) -> bool:
    """Raw frames carry fixed-itemsize buffers only; object/datetime dtypes
    and rank > 8 fall back to the pickled hub plane."""
    return (not arr.dtype.hasobject and arr.dtype.kind not in "OMm"
            and arr.ndim <= _MAX_DIMS)


def _segments(n: int, w: int) -> List[Tuple[int, int]]:
    base, rem = divmod(n, w)
    out, off = [], 0
    for i in range(w):
        size = base + (1 if i < rem else 0)
        out.append((off, size))
        off += size
    return out


def _chunks(off: int, size: int, chunk_elems: int):
    end = off + size
    while off < end:
        n = min(chunk_elems, end - off)
        yield off, n
        off += n


# ---------------------------------------------------------------------------
# Tx thread: decouples chunk sends from the op thread's receives so the
# all-ranks-send-right/recv-left ring step can never deadlock on full
# socket buffers, whatever the chunk size.
# ---------------------------------------------------------------------------


class _TxJob:
    __slots__ = ("event", "error", "nbytes")

    def __init__(self, nbytes: int):
        self.event = threading.Event()
        self.error: Optional[BaseException] = None
        self.nbytes = nbytes


class _TxThread:
    def __init__(self, comm: "TCPCommunicator"):
        self._comm = comm
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"collective-tx-{comm.group_name}-{comm.rank}")
        self._thread.start()

    def submit(self, sock, views: List, deadline: float) -> _TxJob:
        job = _TxJob(sum(v.nbytes for v in views))
        self._q.put((job, sock, views, deadline))
        return job

    def stop(self):
        self._q.put(None)

    def join(self, timeout: float = 2.0):
        self._thread.join(timeout)

    def _run(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            job, sock, views, deadline = item
            try:
                for view in views:
                    _sock_send(sock, view, self._comm.check_abort, deadline)
            except BaseException as e:  # noqa: BLE001 - recorded + surfaced
                job.error = e
                # A failed ring send is a group failure: abort (with KV
                # propagation) so the op thread's blocked receive — and
                # every peer's — unblocks instead of stranding the ring.
                if not isinstance(e, CollectiveAbortError):
                    self._comm.abort(f"ring send failed: {e!r}")
            finally:
                job.event.set()


# ---------------------------------------------------------------------------


class TCPCommunicator(Communicator):
    """Ring-topology process group over TCP (hub plane retained).

    Rendezvous: rank 0 binds an ephemeral port and publishes "host:port"
    through `kv_put(key, value)`; other ranks poll `kv_get(key)`. Every
    rank additionally listens on a p2p port; neighbor/pairwise links form
    lazily on first use and carry the raw-frame data plane.
    """

    def __init__(self, rank: int, world_size: int, group_name: str,
                 kv_put: Callable[[str, str], None],
                 kv_get: Callable[[str], Optional[str]],
                 timeout: float = 120.0,
                 topology: Optional[str] = None,
                 chunk_bytes: Optional[int] = None):
        super().__init__(rank, world_size, group_name)
        from ray_tpu.config import cfg

        self._timeout = timeout
        self._kv_put = kv_put
        self._kv_get = kv_get
        self._topology = topology or cfg().collective_topology
        if self._topology not in ("ring", "hub"):
            raise ValueError(f"unknown collective topology {self._topology!r}")
        self._chunk_override = chunk_bytes
        # Poll granularity for blocking receives: abort flags and deadlines
        # are observed once per tick, so it tracks the watchdog interval.
        self._poll_s = max(0.05, min(cfg().collective_watchdog_interval_s,
                                     1.0))
        # Direct p2p plane: every rank listens; connections form lazily.
        self._p2p_listener = socket.create_server(("127.0.0.1", 0))
        self._p2p_listener.settimeout(self._poll_s)
        kv_put(f"collective:{group_name}:p2p:{rank}",
               f"127.0.0.1:{self._p2p_listener.getsockname()[1]}")
        self._p2p_out: Dict[int, socket.socket] = {}   # dst rank -> socket
        self._p2p_in: Dict[int, socket.socket] = {}    # src rank -> socket
        self._conn_lock = threading.Lock()
        self._accept_lock = threading.Lock()
        # FIFO op plane: all collectives execute on one thread per group in
        # submission order; Work handles complete in that same order.
        self._submit_lock = threading.Lock()
        self._op_seq = 0
        self._op_queue: Optional["queue.SimpleQueue"] = None
        self._op_thread: Optional[threading.Thread] = None
        self._tx: Optional[_TxThread] = None
        key = f"collective:{group_name}"
        if world_size == 1:
            self._peers = []
            return
        if rank == 0:
            # Clear any stale abort flag from a previous same-named group
            # BEFORE publishing the root address (peers only proceed once
            # the address appears, so they can't observe the stale value).
            kv_put(abort_key(group_name), "")
            self._listener = socket.create_server(("127.0.0.1", 0))
            port = self._listener.getsockname()[1]
            kv_put(key, f"127.0.0.1:{port}")
            self._peers: List[Optional[socket.socket]] = [None] * world_size
            deadline = time.monotonic() + timeout
            self._listener.settimeout(timeout)
            connected = 0
            while connected < world_size - 1:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"collective group {group_name}: only {connected + 1}/"
                        f"{world_size} ranks joined")
                sock, _ = self._listener.accept()
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                peer_rank = _recv_msg(sock)
                sock.settimeout(self._poll_s)
                self._peers[peer_rank] = sock
                connected += 1
        else:
            deadline = time.monotonic() + timeout
            addr = None
            while addr is None:
                addr = kv_get(key)
                if addr is None:
                    if time.monotonic() > deadline:
                        raise TimeoutError(f"rendezvous for {group_name} timed out")
                    time.sleep(0.02)
            host, port = addr.rsplit(":", 1)
            self._root = socket.create_connection((host, int(port)), timeout=timeout)
            self._root.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            _send_msg(self._root, rank)
            self._root.settimeout(self._poll_s)
        self._tx = _TxThread(self)
        self._op_queue = queue.SimpleQueue()
        self._op_thread = threading.Thread(
            target=self._op_loop, daemon=True,
            name=f"collective-op-{group_name}-{rank}")
        self._op_thread.start()
        # Liveness/abort watchdog: a dead peer or a KV-set abort flag
        # surfaces CollectiveAbortError in seconds, not the socket timeout.
        self._watchdog = CollectiveWatchdog(self, kv_put, kv_get).start()

    # ---- abort -----------------------------------------------------------

    def abort(self, reason: str = "aborted", propagate: bool = True) -> None:
        """Abort the group: local flag + (by default) the group's KV abort
        key, so every OTHER rank's watchdog aborts within one interval."""
        first = not self.aborted
        super().abort(reason)
        if first and propagate and self.world_size > 1:
            try:
                self._kv_put(abort_key(self.group_name), reason or "aborted")
            except Exception:
                pass
            # First local observation of a real abort (close() passes
            # propagate=False): record it on the cluster event bus so the
            # group-wide unwind is attributable after the fact.
            try:
                from ray_tpu.runtime import events as events_mod

                events_mod.emit(
                    events_mod.COLLECTIVE_ABORT,
                    f"collective group {self.group_name!r} aborted at rank "
                    f"{self.rank}: {reason}",
                    severity=events_mod.ERROR, source="collective",
                    labels={"group": self.group_name,
                            "rank": str(self.rank)})
            except Exception:
                pass

    def _op_deadline(self) -> float:
        from ray_tpu.config import cfg

        return time.monotonic() + cfg().collective_op_timeout_s

    def _chunk_elems(self, itemsize: int) -> int:
        from ray_tpu.config import cfg

        chunk_bytes = (self._chunk_override if self._chunk_override is not None
                       else cfg().collective_chunk_bytes)
        return max(1, int(chunk_bytes) // max(1, itemsize))

    def _ring_fail(self, opname: str, exc: BaseException) -> None:
        """A broken neighbor link mid-op means the group is broken: abort
        (propagating over the KV so ranks NOT adjacent to the failure
        unblock within one watchdog interval) and surface the abort."""
        if not self.aborted:
            self.abort(f"{opname}: ring peer failure ({exc!r})")
        self.check_abort()
        raise ConnectionError(f"{opname}: ring peer failure") from exc

    # ---- FIFO op thread + async handles ----------------------------------

    def _submit(self, fn) -> Work:
        self.check_abort()  # closed/aborted groups reject new ops eagerly
        with self._submit_lock:
            self._op_seq += 1
            work = Work(self._op_seq, self.group_name,
                        rank=self.rank, world_size=self.world_size)
            if self._op_queue is not None:
                self._op_queue.put((work, fn))
                return work
        # world_size == 1: no op thread; complete inline.
        try:
            work._finish(result=fn())
        except BaseException as e:
            work._finish(error=e)
        return work

    def _op_loop(self):
        while True:
            item = self._op_queue.get()
            if item is None:
                return
            work, fn = item
            self._current_op_id = work.op_id  # blocked-on attribution
            try:
                work._finish(result=fn())
            except BaseException as e:  # noqa: BLE001 - delivered at wait()
                work._finish(error=e)

    def _drain(self, jobs: List[_TxJob], deadline: float) -> int:
        """Wait for outstanding tx jobs, observing abort + deadline; returns
        bytes sent and re-raises the first tx error."""
        nbytes = 0
        for job in jobs:
            while not job.event.wait(self._poll_s):
                self.check_abort()
                if time.monotonic() > deadline:
                    raise TimeoutError("collective op deadline exceeded")
            if job.error is not None and not isinstance(job.error,
                                                        CollectiveAbortError):
                raise job.error
            nbytes += job.nbytes
        self.check_abort()
        return nbytes

    # ---- p2p link management ---------------------------------------------

    def _out_sock(self, dst_rank: int, deadline: float) -> socket.socket:
        sock = self._p2p_out.get(dst_rank)
        if sock is not None:
            return sock
        with self._conn_lock:
            sock = self._p2p_out.get(dst_rank)
            if sock is not None:
                return sock
            key = f"collective:{self.group_name}:p2p:{dst_rank}"
            addr = None
            while addr is None:
                self.check_abort()
                addr = self._kv_get(key)
                if addr is None:
                    if time.monotonic() > deadline:
                        raise TimeoutError(f"p2p rendezvous with rank {dst_rank}")
                    time.sleep(0.02)
            host, port = addr.rsplit(":", 1)
            sock = socket.create_connection((host, int(port)),
                                            timeout=self._timeout)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            _send_msg(sock, self.rank)  # identify ourselves
            sock.settimeout(self._poll_s)
            self._p2p_out[dst_rank] = sock
            return sock

    def _in_sock(self, src_rank: int, deadline: float) -> socket.socket:
        sock = self._p2p_in.get(src_rank)
        if sock is not None:
            return sock
        with self._accept_lock:
            while src_rank not in self._p2p_in:
                try:
                    sock, _ = self._p2p_listener.accept()
                except socket.timeout:
                    self.check_abort()
                    if time.monotonic() > deadline:
                        raise TimeoutError(
                            f"p2p recv from rank {src_rank}: deadline exceeded")
                    continue
                except OSError:
                    self.check_abort()
                    raise
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                sock.settimeout(self._poll_s)
                peer = _recv_msg(sock, check=self.check_abort, deadline=deadline)
                self._p2p_in[peer] = sock
            return self._p2p_in[src_rank]

    def _tx_array(self, sock, arr: np.ndarray, deadline: float) -> List[_TxJob]:
        """Queue one array (possibly as multiple chunk frames) on the tx
        thread; frames alias `arr`, which must stay unmodified until the
        returned jobs drain."""
        flat = arr.reshape(-1)
        if flat.size == 0:
            return [self._tx.submit(sock, _frame_views(flat, arr.shape, 0),
                                    deadline)]
        jobs = []
        for off, n in _chunks(0, flat.size, self._chunk_elems(arr.itemsize)):
            jobs.append(self._tx.submit(
                sock, _frame_views(flat[off:off + n], arr.shape, off), deadline))
        return jobs

    def _recv_chunk_into(self, sock, dst: np.ndarray, deadline: float) -> int:
        """Receive exactly one array chunk frame straight into `dst`
        (a contiguous 1-D view sized to the schedule's chunk)."""
        length, kind = _read_hdr(sock, self.check_abort, deadline)
        if kind != _K_ARRAY:
            raise RuntimeError("collective protocol error: expected array frame")
        _, _, _, nelems = _read_ameta(sock, self.check_abort, deadline)
        payload = length - _AMETA.size
        if nelems != dst.size or payload != dst.nbytes:
            raise RuntimeError(
                f"collective protocol error: chunk of {nelems} elems /"
                f" {payload} B where {dst.size} elems / {dst.nbytes} B expected")
        _sock_recv_into(sock, memoryview(dst).cast("B"), self.check_abort,
                        deadline)
        _ser.counters["deserialize_fast"] += 1
        return payload

    # ---- hub (root-coordinated) plane ------------------------------------

    def _coordinate(self, opcode: str, payload, compute):
        """Root: gather payloads from all ranks, run `compute(payloads)->
        per-rank replies`, scatter. Non-root: send payload, await reply."""
        if self.world_size == 1:
            self.check_abort()
            return compute([payload])[0]
        deadline = self._op_deadline()
        with self._op():
            if self.rank == 0:
                payloads: List = [None] * self.world_size
                payloads[0] = payload
                for r in range(1, self.world_size):
                    op, data = _recv_msg(self._peers[r],
                                         check=self.check_abort,
                                         deadline=deadline)
                    assert op == opcode, f"collective mismatch: {op} vs {opcode}"
                    payloads[r] = data
                replies = compute(payloads)
                for r in range(1, self.world_size):
                    _send_msg(self._peers[r], replies[r],
                              check=self.check_abort, deadline=deadline)
                return replies[0]
            _send_msg(self._root, (opcode, payload),
                      check=self.check_abort, deadline=deadline)
            return _recv_msg(self._root, check=self.check_abort,
                             deadline=deadline)

    def _hub_allreduce(self, array: np.ndarray, op: str) -> np.ndarray:
        def compute(payloads):
            result = reduce_arrays(payloads, op)
            return [result] * self.world_size

        with self._timed("allreduce", "hub"):
            return self._coordinate("allreduce", np.asarray(array), compute)

    def _hub_allgather(self, array: np.ndarray) -> List[np.ndarray]:
        def compute(payloads):
            return [list(payloads)] * self.world_size

        with self._timed("allgather", "hub"):
            return self._coordinate("allgather", np.asarray(array), compute)

    def _hub_reducescatter(self, arrays: Sequence[np.ndarray],
                           op: str) -> np.ndarray:
        def compute(payloads):
            # payloads[r] is a list of world_size shards from rank r.
            return [reduce_arrays([p[r] for p in payloads], op)
                    for r in range(self.world_size)]

        with self._timed("reducescatter", "hub"):
            return self._coordinate("reducescatter",
                                    [np.asarray(a) for a in arrays], compute)

    def _hub_broadcast(self, array, src_rank: int) -> np.ndarray:
        def compute(payloads):
            return [payloads[src_rank]] * self.world_size

        payload = np.asarray(array) if self.rank == src_rank else None
        with self._timed("broadcast", "hub"):
            return self._coordinate("broadcast", payload, compute)

    # ---- ring plane ------------------------------------------------------

    def _ring_allreduce(self, arr: np.ndarray, op: str) -> np.ndarray:
        """Bandwidth-optimal chunked ring allreduce: W-1 reduce-scatter
        steps then W-1 all-gather steps over the neighbor links; each rank
        moves 2*(W-1)/W of the buffer total, in `collective_chunk_bytes`
        chunks that pipeline across hops."""
        w, r = self.world_size, self.rank
        if op not in ("sum", "prod", "min", "max", "mean"):
            raise ValueError(f"unknown reduce op {op!r}")
        if w == 1:
            with self._op():
                self.check_abort()
                return reduce_arrays([arr], op)
        rop = "sum" if op == "mean" else op
        flat = arr.flatten()  # private contiguous working copy
        deadline = self._op_deadline()
        sent = recvd = 0
        t0 = time.perf_counter()
        with self._op():
            try:
                right = self._out_sock((r + 1) % w, deadline)
                left = self._in_sock((r - 1) % w, deadline)
                segs = _segments(flat.size, w)
                ch = self._chunk_elems(flat.itemsize)
                scratch = np.empty(min(ch, max(s for _, s in segs) or 1),
                                   flat.dtype)
                # No per-step barrier: every queued frame aliases a segment
                # that is FINAL at queue time and is never rewritten before
                # causal delivery (a later recv that would overwrite it can
                # only complete after the frame has circled the ring), so
                # all tx jobs drain once at op end and consecutive steps'
                # chunks stream back to back through the socket.
                jobs: List[_TxJob] = []
                # Phase 1: ring reduce-scatter. After step t each rank holds
                # a t+2-rank partial of one more segment; after W-1 steps
                # rank r owns the fully reduced segment (r+1) % W.
                for step in range(w - 1):
                    si = (r - step) % w
                    ri = (r - step - 1) % w
                    jobs += [self._tx.submit(right,
                                             _frame_views(flat[o:o + n]),
                                             deadline)
                             for o, n in _chunks(*segs[si], ch)]
                    for o, n in _chunks(*segs[ri], ch):
                        buf = scratch[:n]
                        recvd += self._recv_chunk_into(left, buf, deadline)
                        _reduce_into(flat[o:o + n], buf, rop)
                # Phase 2: ring all-gather of the reduced segments.
                for step in range(w - 1):
                    si = (r - step + 1) % w
                    ri = (r - step) % w
                    jobs += [self._tx.submit(right,
                                             _frame_views(flat[o:o + n]),
                                             deadline)
                             for o, n in _chunks(*segs[si], ch)]
                    for o, n in _chunks(*segs[ri], ch):
                        recvd += self._recv_chunk_into(left, flat[o:o + n],
                                                       deadline)
                sent += self._drain(jobs, deadline)
            except TimeoutError:
                raise
            except (ConnectionError, OSError) as e:
                self._ring_fail("allreduce", e)
        if op == "mean":
            flat = _mean_div(flat, w)
        self._observe("allreduce", "ring", time.perf_counter() - t0, sent, recvd)
        return flat.reshape(arr.shape)

    def _ring_allgather(self, arr: np.ndarray) -> List[np.ndarray]:
        w, r = self.world_size, self.rank
        if w == 1:
            with self._op():
                self.check_abort()
                return [np.asarray(arr)]
        out: List[Optional[np.ndarray]] = [None] * w
        out[r] = np.ascontiguousarray(arr)
        deadline = self._op_deadline()
        sent = recvd = 0
        t0 = time.perf_counter()
        with self._op():
            try:
                right = self._out_sock((r + 1) % w, deadline)
                left = self._in_sock((r - 1) % w, deadline)
                jobs: List[_TxJob] = []
                for step in range(w - 1):
                    si = (r - step) % w
                    ri = (r - step - 1) % w
                    jobs += self._tx_array(right, out[si], deadline)
                    out[ri] = _recv_msg(left, check=self.check_abort,
                                        deadline=deadline)
                    recvd += out[ri].nbytes
                sent += self._drain(jobs, deadline)
            except TimeoutError:
                raise
            except (ConnectionError, OSError) as e:
                self._ring_fail("allgather", e)
        self._observe("allgather", "ring", time.perf_counter() - t0, sent, recvd)
        return out

    def _ring_reducescatter(self, arrays: Sequence[np.ndarray],
                            op: str) -> np.ndarray:
        w, r = self.world_size, self.rank
        arrays = [np.asarray(a) for a in arrays]
        if len(arrays) != w:
            raise ValueError(f"reducescatter needs {w} shards, got {len(arrays)}")
        if w == 1:
            with self._op():
                self.check_abort()
                return reduce_arrays([arrays[0]], op)
        rop = "sum" if op == "mean" else op
        deadline = self._op_deadline()
        sent = recvd = 0
        t0 = time.perf_counter()
        # Running partial: start with our own contribution to the shard the
        # left neighbor chain will accumulate next.
        acc = arrays[(r - 1) % w].flatten()
        with self._op():
            try:
                right = self._out_sock((r + 1) % w, deadline)
                left = self._in_sock((r - 1) % w, deadline)
                jobs: List[_TxJob] = []
                for step in range(w - 1):
                    jobs += self._tx_array(right, acc, deadline)
                    si = (r - step - 2) % w
                    incoming = _recv_msg(left, check=self.check_abort,
                                         deadline=deadline)
                    recvd += incoming.nbytes
                    local = np.ascontiguousarray(arrays[si]).reshape(-1)
                    _reduce_into(incoming, local, rop)
                    acc = incoming
                sent += self._drain(jobs, deadline)
            except TimeoutError:
                raise
            except (ConnectionError, OSError) as e:
                self._ring_fail("reducescatter", e)
        if op == "mean":
            acc = _mean_div(acc, w)
        self._observe("reducescatter", "ring", time.perf_counter() - t0,
                      sent, recvd)
        return acc.reshape(arrays[r].shape)

    def _ring_broadcast(self, arr, src_rank: int) -> np.ndarray:
        """Pipelined chain broadcast: src streams chunks to its right
        neighbor; every other rank forwards each chunk as it lands, so a
        large tensor occupies all hops simultaneously."""
        w, r = self.world_size, self.rank
        if w == 1:
            with self._op():
                self.check_abort()
                return np.asarray(arr)
        deadline = self._op_deadline()
        sent = recvd = 0
        t0 = time.perf_counter()
        with self._op():
            try:
                if r == src_rank:
                    right = self._out_sock((r + 1) % w, deadline)
                    a = np.ascontiguousarray(np.asarray(arr))
                    sent += self._drain(self._tx_array(right, a, deadline),
                                        deadline)
                    self._observe("broadcast", "ring",
                                  time.perf_counter() - t0, sent, recvd)
                    return a
                left = self._in_sock((r - 1) % w, deadline)
                forward = (r + 1) % w != src_rank
                right = self._out_sock((r + 1) % w, deadline) if forward else None
                jobs: List[_TxJob] = []
                out = flat = None
                got = total = 0
                while out is None or got < total:
                    length, kind = _read_hdr(left, self.check_abort, deadline)
                    if kind != _K_ARRAY:
                        raise RuntimeError(
                            "collective protocol error: expected array frame")
                    dtype, shape, offset, nelems = _read_ameta(
                        left, self.check_abort, deadline)
                    if out is None:
                        out = np.empty(shape, dtype)
                        flat = out.reshape(-1)
                        total = flat.size
                    chunk = flat[offset:offset + nelems]
                    _sock_recv_into(left, memoryview(chunk).cast("B"),
                                    self.check_abort, deadline)
                    _ser.counters["deserialize_fast"] += 1
                    recvd += chunk.nbytes
                    got += nelems
                    if forward:
                        jobs.append(self._tx.submit(
                            right, _frame_views(chunk, shape, offset), deadline))
                    if total == 0:
                        break
                sent += self._drain(jobs, deadline)
            except TimeoutError:
                raise
            except (ConnectionError, OSError) as e:
                self._ring_fail("broadcast", e)
        self._observe("broadcast", "ring", time.perf_counter() - t0, sent, recvd)
        return out

    def _alltoall_impl(self, arrays: List[np.ndarray]) -> List[np.ndarray]:
        """Pairwise exchange over the direct p2p links: at offset k every
        rank streams its shard to rank+k while receiving from rank-k —
        sends ride the tx thread, so the exchange is deadlock-free and each
        round keeps both link directions busy."""
        w, r = self.world_size, self.rank
        if len(arrays) != w:
            raise ValueError(f"alltoall needs {w} shards, got {len(arrays)}")
        out: List[Optional[np.ndarray]] = [None] * w
        out[r] = np.asarray(arrays[r])
        if w == 1:
            with self._op():
                self.check_abort()
                return out
        deadline = self._op_deadline()
        sent = recvd = 0
        t0 = time.perf_counter()
        with self._op():
            try:
                jobs: List[_TxJob] = []
                for offset in range(1, w):
                    dst = (r + offset) % w
                    src = (r - offset) % w
                    osock = self._out_sock(dst, deadline)
                    isock = self._in_sock(src, deadline)
                    shard = np.asarray(arrays[dst])
                    if _ring_wire_ok(shard):
                        jobs += self._tx_array(
                            osock, np.ascontiguousarray(shard), deadline)
                    else:
                        jobs.append(self._tx.submit(osock, _ctrl_views(shard),
                                                    deadline))
                    out[src] = _recv_msg(isock, check=self.check_abort,
                                         deadline=deadline)
                    recvd += getattr(out[src], "nbytes", 0)
                sent += self._drain(jobs, deadline)
            except TimeoutError:
                raise
            except (ConnectionError, OSError) as e:
                self._ring_fail("alltoall", e)
        self._observe("alltoall", "ring", time.perf_counter() - t0, sent, recvd)
        return out

    # ---- public collective API -------------------------------------------

    def _ring_enabled(self) -> bool:
        return self._topology == "ring" and self.world_size > 1

    def allreduce_async(self, array: np.ndarray, op: str = "sum") -> Work:
        arr = np.asarray(array)
        if self._ring_enabled() and _ring_wire_ok(arr):
            return self._submit(lambda: self._ring_allreduce(arr, op))
        return self._submit(lambda: self._hub_allreduce(arr, op))

    def allgather_async(self, array: np.ndarray) -> Work:
        arr = np.asarray(array)
        if self._ring_enabled() and _ring_wire_ok(arr):
            return self._submit(lambda: self._ring_allgather(arr))
        return self._submit(lambda: self._hub_allgather(arr))

    def reducescatter_async(self, arrays: Sequence[np.ndarray],
                            op: str = "sum") -> Work:
        arrs = [np.asarray(a) for a in arrays]
        if self._ring_enabled() and all(_ring_wire_ok(a) for a in arrs):
            return self._submit(lambda: self._ring_reducescatter(arrs, op))
        return self._submit(lambda: self._hub_reducescatter(arrs, op))

    def broadcast_async(self, array, src_rank: int = 0) -> Work:
        ok = array is None or _ring_wire_ok(np.asarray(array))
        if self._ring_enabled() and ok:
            return self._submit(lambda: self._ring_broadcast(array, src_rank))
        return self._submit(lambda: self._hub_broadcast(array, src_rank))

    def allreduce(self, array: np.ndarray, op: str = "sum") -> np.ndarray:
        return self.allreduce_async(array, op).wait()

    def allgather(self, array: np.ndarray) -> List[np.ndarray]:
        return self.allgather_async(array).wait()

    def reducescatter(self, arrays: Sequence[np.ndarray],
                      op: str = "sum") -> np.ndarray:
        return self.reducescatter_async(arrays, op).wait()

    def broadcast(self, array: np.ndarray, src_rank: int = 0) -> np.ndarray:
        return self.broadcast_async(array, src_rank).wait()

    def alltoall(self, arrays: Sequence[np.ndarray]) -> List[np.ndarray]:
        return self._submit(lambda: self._alltoall_impl(list(arrays))).wait()

    def barrier(self) -> None:
        # Rides the op thread so a barrier also fences every previously
        # submitted async op on this rank (FIFO drain), then syncs ranks
        # over the root star links.
        self._submit(lambda: self._coordinate(
            "barrier", None, lambda payloads: [None] * self.world_size)).wait()

    # ---- metrics ---------------------------------------------------------

    def _observe(self, opname: str, algo: str, seconds: float,
                 sent: int, recvd: int) -> None:
        try:
            m = _op_metrics(opname, algo)
            m["ops"].inc()
            m["latency"].observe(seconds)
            if sent:
                m["sent"].inc(sent)
            if recvd:
                m["recv"].inc(recvd)
        except Exception:
            pass  # metrics must never break the data plane

    class _Timed:
        __slots__ = ("comm", "opname", "algo", "t0")

        def __init__(self, comm, opname, algo):
            self.comm, self.opname, self.algo = comm, opname, algo

        def __enter__(self):
            self.t0 = time.perf_counter()

        def __exit__(self, exc_type, exc, tb):
            if exc_type is None:
                self.comm._observe(self.opname, self.algo,
                                   time.perf_counter() - self.t0, 0, 0)
            return False

    def _timed(self, opname: str, algo: str) -> "_Timed":
        return TCPCommunicator._Timed(self, opname, algo)

    # ---- p2p (direct pairwise connections) -------------------------------

    def send(self, array: np.ndarray, dst_rank: int) -> None:
        arr = np.asarray(array)
        deadline = self._op_deadline()
        with self._op():
            sock = self._out_sock(
                dst_rank, min(deadline, time.monotonic() + self._timeout))
            if not _ring_wire_ok(arr):
                _send_msg(sock, arr, check=self.check_abort, deadline=deadline)
                return
            # Inline (not via tx): p2p send is one-directional, so it can't
            # deadlock, and staying off the tx queue keeps user p2p from
            # interleaving with an op-thread collective's frames.
            flat = np.ascontiguousarray(arr).reshape(-1)
            if flat.size == 0:
                for view in _frame_views(flat, arr.shape, 0):
                    _sock_send(sock, view, self.check_abort, deadline)
                return
            for off, n in _chunks(0, flat.size, self._chunk_elems(arr.itemsize)):
                for view in _frame_views(flat[off:off + n], arr.shape, off):
                    _sock_send(sock, view, self.check_abort, deadline)

    def recv(self, shape, dtype, src_rank: int) -> np.ndarray:
        deadline = self._op_deadline()
        with self._op():
            sock = self._in_sock(src_rank, deadline)
            return _recv_msg(sock, check=self.check_abort, deadline=deadline)

    def close(self) -> None:
        # Local-only abort: unblocks any thread of THIS rank still inside a
        # collective, without poisoning peers that are shutting down cleanly.
        self.abort("collective group closed", propagate=False)
        if self._watchdog is not None:
            self._watchdog.stop()
        if self._op_queue is not None:
            self._op_queue.put(None)
        if self._tx is not None:
            self._tx.stop()
        try:
            for sock in list(self._p2p_out.values()) + list(self._p2p_in.values()):
                sock.close()
            self._p2p_listener.close()
            if self.world_size > 1:
                if self.rank == 0:
                    for sock in self._peers:
                        if sock is not None:
                            sock.close()
                    self._listener.close()
                else:
                    self._root.close()
        except Exception:
            pass
        if self._op_thread is not None:
            self._op_thread.join(2.0)
        if self._tx is not None:
            self._tx.join(2.0)


# ---------------------------------------------------------------------------
# Per-(op, algo) metric handles, bound once (tag-key precomputation) so the
# per-op bookkeeping stays off the chunk hot path.
# ---------------------------------------------------------------------------

_METRIC_CACHE: Dict[Tuple[str, str], Dict] = {}
_METRIC_LOCK = threading.Lock()


def _op_metrics(opname: str, algo: str) -> Dict:
    key = (opname, algo)
    handles = _METRIC_CACHE.get(key)
    if handles is None:
        from ray_tpu.runtime import metric_defs as md

        with _METRIC_LOCK:
            handles = _METRIC_CACHE.get(key)
            if handles is None:
                tags = {"op": opname, "algo": algo}
                handles = {
                    "ops": md.COLLECTIVE_OPS.bind(tags),
                    "sent": md.COLLECTIVE_BYTES_SENT.bind(tags),
                    "recv": md.COLLECTIVE_BYTES_RECV.bind(tags),
                    "latency": md.COLLECTIVE_OP_LATENCY.bind(tags),
                }
                _METRIC_CACHE[key] = handles
    return handles
