"""TCP star-topology communicator: the CPU/gloo-analog backend.

Reference analog: python/ray/util/collective/collective_group/
gloo_collective_group.py:184 GLOOGroup. Rank 0 coordinates: gathers
contributions, reduces, fans results back out. Bandwidth-optimal rings are
unnecessary here — this backend exists for tests and small control-plane
arrays; the TPU data plane uses in-graph lax collectives (see
ray_tpu/collective/jax_group.py).
"""

from __future__ import annotations

import pickle
import socket
import struct
import threading
import time
from typing import Callable, List, Optional, Sequence

import numpy as np

from ray_tpu.collective.communicator import (
    Communicator, CollectiveWatchdog, abort_key, reduce_arrays)

_HDR = struct.Struct("<Q")


def _send_msg(sock: socket.socket, obj, check: Optional[Callable] = None,
              deadline: Optional[float] = None) -> None:
    data = pickle.dumps(obj, protocol=5)
    payload = memoryview(_HDR.pack(len(data)) + data)
    if check is None and deadline is None:
        sock.sendall(payload)
        return
    # Poll-timeout sockets: a partial send to a slow peer must not surface
    # as a spurious socket.timeout — retry each tick, observing abort flag
    # and per-op deadline just like _recv_msg.
    while payload:
        try:
            sent = sock.send(payload)
        except socket.timeout:
            if check is not None:
                check()
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("collective op deadline exceeded")
            continue
        except OSError:
            if check is not None:
                check()
            raise
        payload = payload[sent:]


def _recv_msg(sock: socket.socket, check: Optional[Callable] = None,
              deadline: Optional[float] = None):
    """Length-prefixed pickle read. With `check`/`deadline` set (and the
    socket on a short poll timeout), each timeout tick runs `check()` —
    which raises CollectiveAbortError once the group's abort flag is set —
    and enforces the per-op deadline, so a blocked receive unblocks within
    one poll tick of an abort instead of the full socket timeout."""

    def _read(n: int) -> bytes:
        parts: List[bytes] = []
        got = 0
        while got < n:
            try:
                chunk = sock.recv(min(1 << 20, n - got))
            except socket.timeout:
                if check is None and deadline is None:
                    raise  # legacy blocking behavior (rendezvous paths)
                if check is not None:
                    check()
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(
                        "collective op deadline exceeded")
                continue
            except OSError:
                # close() sets the abort flag then closes sockets; the
                # abort is the real story, not the EBADF it causes.
                if check is not None:
                    check()
                raise
            if not chunk:
                if check is not None:
                    check()
                raise ConnectionError("collective peer disconnected")
            parts.append(chunk)
            got += len(chunk)
        return b"".join(parts)

    (length,) = _HDR.unpack(_read(_HDR.size))
    return pickle.loads(_read(length))


class TCPCommunicator(Communicator):
    """Star-topology process group over TCP.

    Rendezvous: rank 0 binds an ephemeral port and publishes "host:port"
    through `kv_put(key, value)`; other ranks poll `kv_get(key)`.
    """

    def __init__(self, rank: int, world_size: int, group_name: str,
                 kv_put: Callable[[str, str], None],
                 kv_get: Callable[[str], Optional[str]],
                 timeout: float = 120.0):
        super().__init__(rank, world_size, group_name)
        from ray_tpu.config import cfg

        self._timeout = timeout
        self._kv_put = kv_put
        self._kv_get = kv_get
        # Poll granularity for blocking receives: abort flags and deadlines
        # are observed once per tick, so it tracks the watchdog interval.
        self._poll_s = max(0.05, min(cfg().collective_watchdog_interval_s,
                                     1.0))
        # Direct p2p plane: every rank listens; connections form lazily.
        self._p2p_listener = socket.create_server(("127.0.0.1", 0))
        self._p2p_listener.settimeout(self._poll_s)
        kv_put(f"collective:{group_name}:p2p:{rank}",
               f"127.0.0.1:{self._p2p_listener.getsockname()[1]}")
        self._p2p_out: dict = {}   # dst rank -> socket
        self._p2p_in: dict = {}    # src rank -> socket
        key = f"collective:{group_name}"
        if world_size == 1:
            self._peers = []
            return
        if rank == 0:
            # Clear any stale abort flag from a previous same-named group
            # BEFORE publishing the root address (peers only proceed once
            # the address appears, so they can't observe the stale value).
            kv_put(abort_key(group_name), "")
            self._listener = socket.create_server(("127.0.0.1", 0))
            port = self._listener.getsockname()[1]
            kv_put(key, f"127.0.0.1:{port}")
            self._peers: List[Optional[socket.socket]] = [None] * world_size
            deadline = time.monotonic() + timeout
            self._listener.settimeout(timeout)
            connected = 0
            while connected < world_size - 1:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"collective group {group_name}: only {connected + 1}/"
                        f"{world_size} ranks joined")
                sock, _ = self._listener.accept()
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                peer_rank = _recv_msg(sock)
                sock.settimeout(self._poll_s)
                self._peers[peer_rank] = sock
                connected += 1
        else:
            deadline = time.monotonic() + timeout
            addr = None
            while addr is None:
                addr = kv_get(key)
                if addr is None:
                    if time.monotonic() > deadline:
                        raise TimeoutError(f"rendezvous for {group_name} timed out")
                    time.sleep(0.02)
            host, port = addr.rsplit(":", 1)
            self._root = socket.create_connection((host, int(port)), timeout=timeout)
            self._root.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            _send_msg(self._root, rank)
            self._root.settimeout(self._poll_s)
        # Liveness/abort watchdog: a dead peer or a KV-set abort flag
        # surfaces CollectiveAbortError in seconds, not the socket timeout.
        self._watchdog = CollectiveWatchdog(self, kv_put, kv_get).start()

    # ---- abort -----------------------------------------------------------

    def abort(self, reason: str = "aborted", propagate: bool = True) -> None:
        """Abort the group: local flag + (by default) the group's KV abort
        key, so every OTHER rank's watchdog aborts within one interval."""
        first = not self.aborted
        super().abort(reason)
        if first and propagate and self.world_size > 1:
            try:
                self._kv_put(abort_key(self.group_name), reason or "aborted")
            except Exception:
                pass

    def _op_deadline(self) -> float:
        from ray_tpu.config import cfg

        return time.monotonic() + cfg().collective_op_timeout_s

    # ---- root-coordinated collectives ------------------------------------

    def _coordinate(self, opcode: str, payload, compute):
        """Root: gather payloads from all ranks, run `compute(payloads)->
        per-rank replies`, scatter. Non-root: send payload, await reply."""
        if self.world_size == 1:
            self.check_abort()
            return compute([payload])[0]
        deadline = self._op_deadline()
        with self._op():
            if self.rank == 0:
                payloads: List = [None] * self.world_size
                payloads[0] = payload
                for r in range(1, self.world_size):
                    op, data = _recv_msg(self._peers[r],
                                         check=self.check_abort,
                                         deadline=deadline)
                    assert op == opcode, f"collective mismatch: {op} vs {opcode}"
                    payloads[r] = data
                replies = compute(payloads)
                for r in range(1, self.world_size):
                    _send_msg(self._peers[r], replies[r],
                              check=self.check_abort, deadline=deadline)
                return replies[0]
            _send_msg(self._root, (opcode, payload),
                      check=self.check_abort, deadline=deadline)
            return _recv_msg(self._root, check=self.check_abort,
                             deadline=deadline)

    def allreduce(self, array: np.ndarray, op: str = "sum") -> np.ndarray:
        def compute(payloads):
            result = reduce_arrays(payloads, op)
            return [result] * self.world_size

        return self._coordinate("allreduce", np.asarray(array), compute)

    def allgather(self, array: np.ndarray) -> List[np.ndarray]:
        def compute(payloads):
            return [list(payloads)] * self.world_size

        return self._coordinate("allgather", np.asarray(array), compute)

    def reducescatter(self, arrays: Sequence[np.ndarray], op: str = "sum") -> np.ndarray:
        def compute(payloads):
            # payloads[r] is a list of world_size shards from rank r.
            return [reduce_arrays([p[r] for p in payloads], op)
                    for r in range(self.world_size)]

        return self._coordinate("reducescatter", [np.asarray(a) for a in arrays],
                                compute)

    def broadcast(self, array: np.ndarray, src_rank: int = 0) -> np.ndarray:
        def compute(payloads):
            return [payloads[src_rank]] * self.world_size

        payload = np.asarray(array) if self.rank == src_rank else None
        return self._coordinate("broadcast", payload, compute)

    def barrier(self) -> None:
        self._coordinate("barrier", None, lambda payloads: [None] * self.world_size)

    # ---- p2p (direct pairwise connections) -------------------------------

    def send(self, array: np.ndarray, dst_rank: int) -> None:
        self.check_abort()
        sock = self._p2p_out.get(dst_rank)
        if sock is None:
            key = f"collective:{self.group_name}:p2p:{dst_rank}"
            deadline = time.monotonic() + self._timeout
            addr = None
            while addr is None:
                self.check_abort()
                addr = self._kv_get(key)
                if addr is None:
                    if time.monotonic() > deadline:
                        raise TimeoutError(f"p2p rendezvous with rank {dst_rank}")
                    time.sleep(0.02)
            host, port = addr.rsplit(":", 1)
            sock = socket.create_connection((host, int(port)), timeout=self._timeout)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            _send_msg(sock, self.rank)  # identify ourselves
            sock.settimeout(self._poll_s)
            self._p2p_out[dst_rank] = sock
        _send_msg(sock, np.asarray(array), check=self.check_abort,
                  deadline=self._op_deadline())

    def recv(self, shape, dtype, src_rank: int) -> np.ndarray:
        deadline = self._op_deadline()
        with self._op():
            while src_rank not in self._p2p_in:
                try:
                    sock, _ = self._p2p_listener.accept()
                except socket.timeout:
                    self.check_abort()
                    if time.monotonic() > deadline:
                        raise TimeoutError(
                            f"p2p recv from rank {src_rank}: deadline exceeded")
                    continue
                except OSError:
                    self.check_abort()
                    raise
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                sock.settimeout(self._poll_s)
                peer = _recv_msg(sock, check=self.check_abort, deadline=deadline)
                self._p2p_in[peer] = sock
            return _recv_msg(self._p2p_in[src_rank], check=self.check_abort,
                             deadline=deadline)

    def close(self) -> None:
        # Local-only abort: unblocks any thread of THIS rank still inside a
        # collective, without poisoning peers that are shutting down cleanly.
        self.abort("collective group closed", propagate=False)
        if self._watchdog is not None:
            self._watchdog.stop()
        try:
            for sock in list(self._p2p_out.values()) + list(self._p2p_in.values()):
                sock.close()
            self._p2p_listener.close()
            if self.world_size > 1:
                if self.rank == 0:
                    for sock in self._peers:
                        if sock is not None:
                            sock.close()
                    self._listener.close()
                else:
                    self._root.close()
        except Exception:
            pass
