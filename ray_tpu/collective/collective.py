"""Collective API over named process groups.

Reference analog: python/ray/util/collective/collective.py
(init_collective_group:123, allreduce:268, barrier:308, broadcast:383,
allgather:433, reducescatter:482, send:541, recv:604). Rendezvous goes
through the GCS KV (the reference stores the NCCL unique id in a named actor;
a KV entry is the same pattern one level lower).

Backends:
  * "tcp"  — TCPCommunicator (CPU/gloo analog; tests and control plane).
             Data plane is chunked ring algorithms over per-rank p2p links
             with zero-pickle raw-buffer frames (cfg().collective_topology
             selects "ring"/"hub"; see docs/collectives.md). Async handles
             (`allreduce_async(...) -> Work`) complete in FIFO order on a
             per-group op thread.
  * "jax"  — multi-host jax.distributed bootstrap; collectives then run
             in-graph over ICI (see jax_backend.initialize_jax_distributed)
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ray_tpu.collective.communicator import Communicator
from ray_tpu.collective.cpu_group import TCPCommunicator

_groups: Dict[str, Communicator] = {}


def _gcs_kv():
    from ray_tpu.core.worker import global_worker

    core = global_worker()

    def kv_put(key: str, value: str):
        core.io.run(core.gcs.call("kv_put", key=key.encode(), value=value.encode()))

    def kv_get(key: str) -> Optional[str]:
        reply = core.io.run(core.gcs.call("kv_get", key=key.encode()))
        return reply["value"].decode() if reply["value"] is not None else None

    return kv_put, kv_get


def init_collective_group(world_size: int, rank: int, backend: str = "tcp",
                          group_name: str = "default") -> Communicator:
    if group_name in _groups:
        raise ValueError(f"collective group {group_name!r} already initialized")
    kv_put, kv_get = _gcs_kv()
    if backend == "tcp":
        comm = TCPCommunicator(rank, world_size, group_name, kv_put, kv_get)
    elif backend == "jax":
        from ray_tpu.collective.jax_backend import JaxDistributedCommunicator
        comm = JaxDistributedCommunicator(rank, world_size, group_name, kv_put, kv_get)
    else:
        raise ValueError(f"unknown collective backend {backend!r}")
    _groups[group_name] = comm
    return comm


def abort_collective_group(group_name: str = "default",
                           reason: str = "aborted") -> None:
    """Abort a group's in-flight and future ops everywhere.

    Writes the group's KV abort flag — every member rank's watchdog picks it
    up within one `collective_watchdog_interval_s` and raises
    CollectiveAbortError out of any blocked op. Callable from ANY process
    that can reach the GCS (e.g. the Train controller during gang restart),
    not just group members. If this process holds the group, it is also
    aborted locally (immediate, no watchdog latency).
    """
    from ray_tpu.collective.communicator import abort_key

    comm = _groups.get(group_name)
    if comm is not None:
        comm.abort(reason)  # immediate locally; TCP also writes the KV
        try:
            kv_put, _ = _gcs_kv()
            kv_put(abort_key(group_name), reason or "aborted")
        except Exception:
            pass  # no GCS reachable: the local abort still happened
        return
    kv_put, _ = _gcs_kv()
    kv_put(abort_key(group_name), reason or "aborted")


def destroy_collective_group(group_name: str = "default"):
    comm = _groups.pop(group_name, None)
    if comm is not None:
        # Unblock any thread still inside a collective before tearing down
        # sockets, so it exits with CollectiveAbortError instead of a
        # confusing ConnectionError from a closed fd.
        comm.close()


def get_group(group_name: str = "default") -> Communicator:
    if group_name not in _groups:
        raise ValueError(f"collective group {group_name!r} is not initialized")
    return _groups[group_name]


def get_rank(group_name: str = "default") -> int:
    return get_group(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return get_group(group_name).world_size


def allreduce(array: np.ndarray, group_name: str = "default", op: str = "sum"):
    return get_group(group_name).allreduce(array, op)


def allreduce_async(array: np.ndarray, group_name: str = "default",
                    op: str = "sum"):
    """Launch an allreduce and return a Work handle; `.wait()` for the
    result. Handles on one group complete in submission (FIFO) order."""
    return get_group(group_name).allreduce_async(array, op)


def allgather(array: np.ndarray, group_name: str = "default") -> List[np.ndarray]:
    return get_group(group_name).allgather(array)


def reducescatter(arrays: Sequence[np.ndarray], group_name: str = "default",
                  op: str = "sum") -> np.ndarray:
    return get_group(group_name).reducescatter(arrays, op)


def broadcast(array: np.ndarray, src_rank: int = 0, group_name: str = "default"):
    return get_group(group_name).broadcast(array, src_rank)


def alltoall(arrays: Sequence[np.ndarray], group_name: str = "default"):
    return get_group(group_name).alltoall(arrays)


def send(array: np.ndarray, dst_rank: int, group_name: str = "default"):
    get_group(group_name).send(array, dst_rank)


def recv(shape, dtype, src_rank: int, group_name: str = "default") -> np.ndarray:
    return get_group(group_name).recv(shape, dtype, src_rank)


def barrier(group_name: str = "default"):
    get_group(group_name).barrier()
