"""Multi-host JAX collective backend: the NCCL-replacement data plane.

Reference analog: util/collective's NCCLGroup (nccl_collective_group.py:128)
and Train's torch.distributed process groups. The TPU-native equivalent:
every worker process calls `jax.distributed.initialize` against a coordinator
whose address rendezvouses through the GCS KV (named-actor pattern in the
reference, collective.py:123). After that, arrays live on the global mesh and
collectives are `jax.lax` ops compiled over ICI (intra-slice) / DCN
(cross-slice) — XLA inserts and schedules the transfers.

The Communicator methods here are out-of-graph conveniences staged through
jit; hot-path training code should instead build meshes with
ray_tpu.parallel and keep collectives inside its compiled step functions.
"""

from __future__ import annotations

import socket
import time
from typing import Callable, List, Optional, Sequence

import numpy as np

from ray_tpu.collective.communicator import Communicator, CollectiveWatchdog

_initialized = False


def initialize_jax_distributed(rank: int, world_size: int, group_name: str,
                               kv_put: Callable[[str, str], None],
                               kv_get: Callable[[str], Optional[str]],
                               timeout: float = 120.0) -> None:
    """Bootstrap jax.distributed across worker processes.

    Rank 0 picks a free port and publishes it; everyone joins. Safe to call
    once per process.
    """
    global _initialized
    if _initialized:
        return
    import jax

    key = f"collective:{group_name}:jax_coordinator"
    if world_size == 1:
        _initialized = True
        return
    if rank == 0:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        coordinator = f"127.0.0.1:{port}"
        kv_put(key, coordinator)
    else:
        deadline = time.monotonic() + timeout
        coordinator = None
        while coordinator is None:
            coordinator = kv_get(key)
            if coordinator is None:
                if time.monotonic() > deadline:
                    raise TimeoutError(f"jax coordinator rendezvous for {group_name}")
                time.sleep(0.02)
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=world_size, process_id=rank)
    _initialized = True


class JaxDistributedCommunicator(Communicator):
    """Out-of-graph collectives over the global jax mesh (all processes)."""

    def __init__(self, rank: int, world_size: int, group_name: str,
                 kv_put, kv_get, timeout: float = 120.0):
        super().__init__(rank, world_size, group_name)
        initialize_jax_distributed(rank, world_size, group_name, kv_put, kv_get,
                                   timeout)
        import jax

        self.jax = jax
        self.devices = jax.devices()  # global, across processes
        self.local_devices = jax.local_devices()
        # jax.distributed blocking collectives can't be interrupted mid-op,
        # but the watchdog still fails the NEXT op fast (check_abort at op
        # entry) and propagates remote aborts / dead-peer detection.
        if world_size > 1:
            self._watchdog = CollectiveWatchdog(self, kv_put, kv_get).start()

    # Helper: stage a host array onto the process-sharded global mesh, apply
    # an in-graph collective, fetch the (replicated) result.
    def _process_mesh(self):
        import numpy as onp
        from jax.sharding import Mesh

        devs = onp.array(self.devices[:self.world_size * len(self.local_devices)])
        # One mesh axis over processes: use the first local device per process.
        per_proc = [d for d in self.devices if d.process_index == self.jax.process_index()]
        first_per_proc = sorted(
            {d.process_index: d for d in self.devices}.items())
        return Mesh(onp.array([d for _, d in first_per_proc]), ("proc",))

    def allreduce(self, array: np.ndarray, op: str = "sum") -> np.ndarray:
        import jax.numpy as jnp
        from jax import shard_map
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        from jax import lax

        self.check_abort()
        mesh = self._process_mesh()
        x = jnp.asarray(array)[None, ...]  # leading axis = proc shard
        sharding = NamedSharding(mesh, P("proc"))
        global_shape = (self.world_size,) + tuple(array.shape)
        arr = self.jax.make_array_from_process_local_data(sharding, np.asarray(x),
                                                          global_shape)

        def reduce_fn(v):
            red = {"sum": lax.psum, "max": lax.pmax, "min": lax.pmin,
                   "mean": lambda t, n: lax.pmean(t, n)}[op]
            return red(v[0], "proc")[None]

        fn = shard_map(reduce_fn, mesh=mesh, in_specs=P("proc"), out_specs=P("proc"))
        out = self.jax.jit(fn)(arr)
        return np.asarray(out.addressable_data(0))[0] if out.addressable_shards \
            else np.asarray(out)[0]

    def allgather(self, array: np.ndarray) -> List[np.ndarray]:
        gathered = self.allreduce_concat(array)
        return [gathered[i] for i in range(self.world_size)]

    def allreduce_concat(self, array: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp
        from jax import shard_map
        from jax.sharding import NamedSharding, PartitionSpec as P
        from jax import lax

        self.check_abort()
        mesh = self._process_mesh()
        x = jnp.asarray(array)[None, ...]
        sharding = NamedSharding(mesh, P("proc"))
        global_shape = (self.world_size,) + tuple(array.shape)
        arr = self.jax.make_array_from_process_local_data(sharding, np.asarray(x),
                                                          global_shape)

        def gather_fn(v):
            return lax.all_gather(v[0], "proc")[None]

        fn = shard_map(gather_fn, mesh=mesh, in_specs=P("proc"),
                       out_specs=P("proc"))
        out = self.jax.jit(fn)(arr)
        return np.asarray(out.addressable_data(0))[0]

    def reducescatter(self, arrays: Sequence[np.ndarray], op: str = "sum"):
        stacked = np.stack([np.asarray(a) for a in arrays])
        reduced = self.allreduce(stacked, op)
        return reduced[self.rank]

    def broadcast(self, array: np.ndarray, src_rank: int = 0) -> np.ndarray:
        contribution = np.asarray(array) if self.rank == src_rank \
            else np.zeros_like(np.asarray(array))
        return self.allreduce(contribution, "sum")

    def send(self, array, dst_rank):
        raise NotImplementedError(
            "p2p over the jax backend is in-graph (lax.ppermute); use the tcp "
            "backend for out-of-graph host p2p")

    def recv(self, shape, dtype, src_rank):
        raise NotImplementedError(
            "p2p over the jax backend is in-graph (lax.ppermute); use the tcp "
            "backend for out-of-graph host p2p")

    def barrier(self) -> None:
        self.allreduce(np.zeros(1, dtype=np.float32), "sum")

    def close(self) -> None:
        if self._watchdog is not None:
            self._watchdog.stop()
