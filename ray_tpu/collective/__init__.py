from ray_tpu.collective.collective import (  # noqa: F401
    abort_collective_group,
    allgather,
    allreduce,
    allreduce_async,
    alltoall,
    barrier,
    broadcast,
    destroy_collective_group,
    get_collective_group_size,
    get_rank,
    init_collective_group,
    recv,
    reducescatter,
    send,
)
from ray_tpu.collective.communicator import Communicator, Work  # noqa: F401
from ray_tpu.core.exceptions import CollectiveAbortError  # noqa: F401
