"""Async sharded checkpoint plane.

Three stages, three modules:

- `snapshot`  — device -> host staging buffers; the only part the train
                step ever waits for.
- `manifest`  — path-based (zero-pickle) leaf tables, per-rank shard
                files, atomic manifest commit.
- `restore`   — manifest -> global leaves -> re-slice for the CURRENT
                world size (reshard-on-restore, N -> M workers).
- `plane`     — the background persister tying them together, plus
                peer replication and GCS relocation registration.

See docs/checkpointing.md for the lifecycle and on-disk format.
"""

from ray_tpu.checkpoint.manifest import (
    CheckpointError,
    CheckpointNotCommitted,
    FORMAT,
    has_manifest,
    read_manifest,
    shard_axis_for,
)
from ray_tpu.checkpoint.plane import (
    CheckpointPlane,
    PendingSave,
    save_sharded,
)
from ray_tpu.checkpoint.restore import restore_shard, restore_tree
from ray_tpu.checkpoint.snapshot import BufferPool, Snapshot, snapshot_shard

__all__ = [
    "BufferPool",
    "CheckpointError",
    "CheckpointNotCommitted",
    "CheckpointPlane",
    "FORMAT",
    "PendingSave",
    "Snapshot",
    "has_manifest",
    "read_manifest",
    "restore_shard",
    "restore_tree",
    "save_sharded",
    "shard_axis_for",
    "snapshot_shard",
]
