"""The checkpoint plane: async save = snapshot now, persist in background.

`save_async` stalls the caller for the device->host snapshot ONLY
(snapshot.py), then hands the captured buffers to a daemon persister
thread which:

  1. writes this rank's shard npz + leaf table (tmp + fsync + rename),
  2. tries the atomic manifest commit (manifest.py — whichever rank
     lands last commits; a crash anywhere leaves the previous
     checkpoint valid),
  3. optionally replicates the completed shard to peer hosts through
     the `util/broadcast.py` fanout tree and registers the replica
     object in the GCS drain relocation table, so a draining node's
     shards are already elsewhere when the deadline kill lands,
  4. books `ray_tpu_ckpt_*` metrics and (committer only) emits the
     CHECKPOINT_SAVED cluster event.

Rank 0's persister additionally waits (bounded, cheap stat polling) for
the manifest commit even when another rank lands it, so exactly one
rank can report "checkpoint real" upstream — that is how the Train
session feeds the controller's CheckpointManager without any
cross-rank RPC.
"""

from __future__ import annotations

import collections
import logging
import os
import queue
import threading
import time
from typing import Any, Callable, List, Optional

from ray_tpu.checkpoint import manifest as manifest_mod
from ray_tpu.checkpoint import snapshot as snapshot_mod
from ray_tpu.checkpoint.manifest import CheckpointError

logger = logging.getLogger(__name__)


class PendingSave:
    """Handle for one rank's in-flight save. `wait()` blocks until the
    background persist finished (NOT until the global commit — rank 0's
    handle observes the commit via `committed`)."""

    def __init__(self, directory: str, name: str, rank: int, world: int,
                 step: Optional[int], snapshot_ms: float, nbytes: int):
        self.directory = directory
        self.name = name
        self.rank = rank
        self.world = world
        self.step = step
        self.snapshot_ms = snapshot_ms
        self.nbytes = nbytes
        self.persist_ms = 0.0
        self.committed = False
        self.error: Optional[BaseException] = None
        self.done = threading.Event()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self.done.wait(timeout)

    @property
    def ok(self) -> bool:
        return self.done.is_set() and self.error is None

    def info(self) -> dict:
        return {"directory": self.directory, "name": self.name,
                "rank": self.rank, "world": self.world, "step": self.step,
                "snapshot_ms": self.snapshot_ms,
                "persist_ms": self.persist_ms, "bytes": self.nbytes,
                "committed": self.committed, "ok": self.ok}


class CheckpointPlane:
    """Per-process checkpoint pipeline: one staging-buffer pool + one
    background persister thread, shared by every save this process makes."""

    def __init__(self, *, reuse_buffers: bool = True, source: str = "train"):
        self.source = source
        self._pool = snapshot_mod.BufferPool() if reuse_buffers else None
        self._queue: "queue.Queue" = queue.Queue()
        self._pending: List[PendingSave] = []
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        # Replica shard objects stay referenced here so the object plane
        # keeps them alive across the drain window (bounded: old
        # checkpoints age out of the deque and become collectable).
        self._replica_refs: "collections.deque" = collections.deque(maxlen=32)

    # -- public API --------------------------------------------------------

    def save_async(self, tree: Any, directory: str, *, name: str = "state",
                   rank: int = 0, world: int = 1,
                   step: Optional[int] = None,
                   wait_commit: Optional[bool] = None,
                   on_done: Optional[Callable[[dict], None]] = None
                   ) -> PendingSave:
        """Snapshot `tree`'s (rank, world) shard into host buffers and
        return; persistence happens on the background thread. The caller
        stalls only for the device->host copy."""
        if self._closed:
            raise CheckpointError("checkpoint plane is closed")
        snap = snapshot_mod.snapshot_shard(tree, rank=rank, world=world,
                                           name=name, pool=self._pool)
        from ray_tpu.runtime import metric_defs

        metric_defs.CKPT_SNAPSHOT_MS.observe(snap.snapshot_ms)
        pending = PendingSave(os.path.abspath(directory), name, rank, world,
                              step, snap.snapshot_ms, snap.nbytes)
        if wait_commit is None:
            wait_commit = rank == 0
        with self._lock:
            self._pending.append(pending)
        self._ensure_thread()
        self._queue.put((snap, pending, wait_commit, on_done))
        return pending

    def save_sync(self, tree: Any, directory: str, *, name: str = "state",
                  rank: int = 0, world: int = 1,
                  step: Optional[int] = None) -> PendingSave:
        """Snapshot AND persist inline on the calling thread — the
        synchronous baseline (and the flush-at-exit path). The caller
        stalls for serialization, fsync, and the commit attempt."""
        snap = snapshot_mod.snapshot_shard(tree, rank=rank, world=world,
                                           name=name, pool=self._pool)
        from ray_tpu.runtime import metric_defs

        metric_defs.CKPT_SNAPSHOT_MS.observe(snap.snapshot_ms)
        pending = PendingSave(os.path.abspath(directory), name, rank, world,
                              step, snap.snapshot_ms, snap.nbytes)
        with self._lock:
            self._pending.append(pending)
        self._persist(snap, pending, wait_commit=False, on_done=None)
        if pending.error is not None:
            raise pending.error
        return pending

    def flush(self, timeout: Optional[float] = None) -> bool:
        """Block until every in-flight persist finished. True iff all
        completed without error inside the timeout. This is what the
        drain path calls AFTER quiescing collectives: the train step
        never waits for persistence, the teardown does."""
        deadline = None if timeout is None else time.monotonic() + timeout
        ok = True
        while True:
            with self._lock:
                pendings = list(self._pending)
            if not pendings:
                return ok
            for p in pendings:
                remaining = None if deadline is None \
                    else max(0.0, deadline - time.monotonic())
                if not p.done.wait(remaining):
                    return False
                ok = ok and p.error is None
            # Loop: a save issued while we waited joins the flush.

    def close(self) -> None:
        self._closed = True
        if self._thread is not None:
            self._queue.put(None)
            self._thread.join(timeout=5)

    # -- persister ---------------------------------------------------------

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="ckpt-persister", daemon=True)
            self._thread.start()

    def _run(self) -> None:
        while True:
            job = self._queue.get()
            if job is None:
                return
            snap, pending, wait_commit, on_done = job
            self._persist(snap, pending, wait_commit, on_done)

    def _persist(self, snap, pending: PendingSave, wait_commit: bool,
                 on_done: Optional[Callable[[dict], None]]) -> None:
        from ray_tpu.config import cfg
        from ray_tpu.runtime import metric_defs
        from ray_tpu.util import fault_injection

        t0 = time.perf_counter()
        committed_manifest = None
        try:
            fault_injection.failpoint("ckpt.persist")
            manifest_mod.write_shard(
                pending.directory, pending.name, pending.rank, pending.world,
                snap.records, snap.leaves, fsync=cfg().ckpt_fsync)
            if cfg().ckpt_replicate:
                self._replicate_shard(pending)
            fault_injection.failpoint("ckpt.commit")
            committed_manifest = manifest_mod.try_commit(
                pending.directory, pending.name, pending.world,
                step=pending.step, fsync=cfg().ckpt_fsync)
            if committed_manifest is not None:
                pending.committed = True
        except BaseException as e:  # noqa: BLE001 - surfaced on the handle
            pending.error = e
            logger.warning("checkpoint persist failed for %s",
                           pending.directory, exc_info=True)
        pending.persist_ms = (time.perf_counter() - t0) * 1e3
        metric_defs.CKPT_PERSIST_MS.observe(pending.persist_ms)
        if pending.error is None:
            metric_defs.CKPT_BYTES.inc(pending.nbytes)
        snap.release()
        if committed_manifest is not None:
            self._emit_saved(pending, committed_manifest)
        if pending.error is None and not pending.committed and wait_commit:
            # This rank's shard is durable but another rank holds the
            # last one. Watch for that commit OFF the persister thread —
            # blocking here would starve queued saves (and, when several
            # ranks share one process/plane, the very save that commits).
            threading.Thread(
                target=self._await_commit, args=(pending, on_done),
                name="ckpt-commit-wait", daemon=True).start()
        else:
            self._finalize(pending, on_done)

    def _await_commit(self, pending: PendingSave,
                      on_done: Optional[Callable[[dict], None]]) -> None:
        from ray_tpu.config import cfg

        try:
            pending.committed = manifest_mod.wait_committed(
                pending.directory, pending.name, cfg().ckpt_commit_wait_s)
        except Exception as e:
            pending.error = e
        self._finalize(pending, on_done)

    def _finalize(self, pending: PendingSave,
                  on_done: Optional[Callable[[dict], None]]) -> None:
        with self._lock:
            try:
                self._pending.remove(pending)
            except ValueError:
                pass
        pending.done.set()
        if on_done is not None:
            try:
                on_done(pending.info())
            except Exception:
                logger.warning("checkpoint on_done callback failed",
                               exc_info=True)

    def _emit_saved(self, pending: PendingSave, committed: dict) -> None:
        """Exactly one rank (the committer) announces the checkpoint."""
        from ray_tpu.runtime import events

        events.emit(
            events.CHECKPOINT_SAVED,
            f"checkpoint {pending.name!r} committed at {pending.directory} "
            f"(step {pending.step}, {committed.get('nbytes', 0)} bytes, "
            f"{pending.world} shard(s))",
            severity=events.INFO, source=self.source,
            labels={"step": str(pending.step),
                    "world": str(pending.world),
                    "bytes": str(committed.get("nbytes", 0)),
                    "snapshot_ms": f"{pending.snapshot_ms:.3f}",
                    "persist_ms": f"{pending.persist_ms:.1f}",
                    "path": pending.directory})

    def _replicate_shard(self, pending: PendingSave) -> int:
        """Fan the durable shard bytes out to peer object stores and
        register the replica in the GCS drain relocation table.
        Best-effort: replication failures never fail the save."""
        try:
            import numpy as np

            import ray_tpu
            from ray_tpu.config import cfg
            from ray_tpu.core import worker as worker_mod
            from ray_tpu.util.broadcast import broadcast_object

            if not ray_tpu.is_initialized():
                return 0
            path = os.path.join(
                pending.directory,
                manifest_mod.shard_npz(pending.name, pending.rank,
                                       pending.world))
            data = np.fromfile(path, dtype=np.uint8)
            ref = ray_tpu.put(data)
            covered = broadcast_object(
                ref, timeout=cfg().ckpt_replicate_timeout_s)
            core = worker_mod.global_worker()
            core.io.run(core.gcs.call(
                "register_checkpoint_shards",
                path=pending.directory, name=pending.name,
                shard=pending.rank, world=pending.world, step=pending.step,
                nbytes=int(data.nbytes), oids=[ref.binary()],
                node_id=core.node_id), timeout=10)
            self._replica_refs.append(ref)
            return covered
        except Exception:
            logger.warning("checkpoint shard replication failed for %s",
                           pending.directory, exc_info=True)
            return 0


def save_sharded(tree: Any, directory: str, *, name: str = "state",
                 rank: int = 0, world: int = 1,
                 step: Optional[int] = None) -> dict:
    """One-shot synchronous save of one rank's shard (world=1 = a whole
    tree in the new manifest format — the `Checkpoint.save_pytree`
    backend). Commits the manifest when this shard completes the set."""
    from ray_tpu.config import cfg

    snap = snapshot_mod.snapshot_shard(tree, rank=rank, world=world,
                                       name=name, pool=None)
    nbytes = manifest_mod.write_shard(directory, name, rank, world,
                                      snap.records, snap.leaves,
                                      fsync=cfg().ckpt_fsync)
    committed = manifest_mod.try_commit(directory, name, world, step=step,
                                        fsync=cfg().ckpt_fsync)
    return {"bytes": nbytes, "committed": committed is not None,
            "snapshot_ms": snap.snapshot_ms}
