"""Checkpoint manifest: path-based leaf records + atomic commit.

The manifest is the zero-pickle replacement for the old
`{name}.treedef.pkl` format (train/checkpoint.py): structure crosses
process and storage boundaries as a JSON table of key paths — the same
idiom as `rlhf/weight_sync.describe_weights` — never as a pickled
treedef. Each record carries the leaf's GLOBAL shape/dtype plus how it
was sharded across the saving world, so restore can reassemble the
global array from any number of shard files and re-slice it for a
*different* world size (reshard-on-restore).

On-disk layout for a checkpoint named `state` saved by an N-rank world:

    state-shard-00000-of-00004.npz    per-rank leaf arrays
    state-shard-00000-of-00004.json   per-rank leaf table + nbytes
    ...
    state.manifest.json               committed LAST, atomically

Commit protocol: every rank's persister writes its shard npz + json
(tmp file, fsync, rename), then calls `try_commit` — whichever rank
finds all N shard jsons present writes `state.manifest.json` via the
same tmp+fsync+rename dance. `os.replace` is atomic on POSIX, so a
crash at ANY point mid-persist leaves either no manifest (checkpoint
never existed; the previous one stays latest) or a complete one. The
manifest content is derived deterministically from the shard tables, so
concurrent committers racing the rename write identical bytes.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_tpu.core.exceptions import RayTpuError

FORMAT = "ray-tpu-ckpt-v1"


class CheckpointError(RayTpuError):
    pass


class CheckpointNotCommitted(CheckpointError):
    """No committed manifest at the path: the save never completed (or
    crashed mid-persist). Callers fall back to the previous checkpoint."""


# -- key paths --------------------------------------------------------------

def encode_path(path) -> List[dict]:
    """JSON-able encoding of a jax key path: dict keys as {"key": k},
    sequence positions as {"idx": i}, attribute nodes (registered
    dataclass-style pytrees) as {"attr": name}. Anything else is an
    error — exotic custom nodes should be captured via a template on
    restore, but their keys still encode one of these three ways."""
    out: List[dict] = []
    for k in path:
        if hasattr(k, "key"):                       # DictKey / FlattenedIndex
            key = k.key
            if not isinstance(key, (str, int)):
                raise CheckpointError(
                    f"non-JSON dict key {key!r} in checkpoint tree")
            out.append({"key": key})
        elif hasattr(k, "idx"):                     # SequenceKey
            out.append({"idx": int(k.idx)})
        elif hasattr(k, "name"):                    # GetAttrKey
            out.append({"attr": str(k.name)})
        else:
            raise CheckpointError(f"unsupported tree key {k!r}")
    return out


def path_str(encoded: Sequence[dict]) -> str:
    return "/".join(str(next(iter(seg.values()))) for seg in encoded) or "."


# -- leaf records -----------------------------------------------------------

def shard_axis_for(shape: Tuple[int, ...], world: int) -> Optional[int]:
    """Axis 0 when the leading dim splits evenly across the world;
    None = replicated (stored by shard 0 only). The rule is recomputed
    at restore time for the NEW world size, so N-way and M-way layouts
    of the same tree are both derivable from the global shape alone."""
    if world > 1 and len(shape) >= 1 and shape[0] >= world \
            and shape[0] % world == 0:
        return 0
    return None


def leaf_records(tree: Any, world: int) -> Tuple[List[dict], List[Any]]:
    """Flatten `tree` with paths into (records, leaves). Records carry
    GLOBAL shape/dtype + shard_axis for `world`; every rank derives the
    identical table from its (replicated) tree."""
    import jax
    import numpy as np

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    records, leaves = [], []
    for path, leaf in flat:
        shape = tuple(int(d) for d in getattr(leaf, "shape", ()))
        dtype = str(np.dtype(getattr(leaf, "dtype", np.asarray(leaf).dtype)))
        records.append({"path": encode_path(path),
                        "global_shape": list(shape),
                        "dtype": dtype,
                        "shard_axis": shard_axis_for(shape, world)})
        leaves.append(leaf)
    return records, leaves


# -- file naming ------------------------------------------------------------

def shard_npz(name: str, rank: int, world: int) -> str:
    return f"{name}-shard-{rank:05d}-of-{world:05d}.npz"


def shard_meta(name: str, rank: int, world: int) -> str:
    return f"{name}-shard-{rank:05d}-of-{world:05d}.json"


def manifest_file(name: str) -> str:
    return f"{name}.manifest.json"


def has_manifest(directory: str, name: str = "state") -> bool:
    return os.path.exists(os.path.join(directory, manifest_file(name)))


# -- durable writes ---------------------------------------------------------

def _fsync_write(path: str, write_fn, fsync: bool = True) -> None:
    """Write via tmp + (fsync) + atomic rename, so readers only ever see
    absent-or-complete files."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        write_fn(f)
        if fsync:
            f.flush()
            os.fsync(f.fileno())
    os.replace(tmp, path)


def _fsync_dir(directory: str) -> None:
    try:
        fd = os.open(directory, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass  # not all filesystems support directory fsync


def write_shard(directory: str, name: str, rank: int, world: int,
                records: Sequence[dict], local_leaves: Sequence,
                fsync: bool = True) -> int:
    """Persist one rank's shard: npz of its local leaf arrays + the json
    leaf table. `local_leaves[i]` is None for leaves this rank does not
    store (replicated leaves on rank > 0). Returns bytes written."""
    import numpy as np

    os.makedirs(directory, exist_ok=True)
    entries = {f"leaf_{i}": np.asarray(l)
               for i, l in enumerate(local_leaves) if l is not None}
    nbytes = int(sum(a.nbytes for a in entries.values()))
    _fsync_write(os.path.join(directory, shard_npz(name, rank, world)),
                 lambda f: np.savez(f, **entries), fsync=fsync)
    meta = {"format": FORMAT, "name": name, "rank": rank, "world": world,
            "nbytes": nbytes, "leaves": list(records)}
    payload = json.dumps(meta).encode()
    _fsync_write(os.path.join(directory, shard_meta(name, rank, world)),
                 lambda f: f.write(payload), fsync=fsync)
    return nbytes


def try_commit(directory: str, name: str, world: int, *,
               step: Optional[int] = None, fsync: bool = True,
               extra: Optional[Dict[str, Any]] = None) -> Optional[dict]:
    """Atomically commit the manifest if (and only if) every shard's meta
    is on disk. Returns the manifest dict when THIS call committed (or
    found it already committed returns None — exactly one caller reports
    the commit), None while shards are still missing."""
    path = os.path.join(directory, manifest_file(name))
    if os.path.exists(path):
        return None  # someone else won the commit race
    tables = []
    for rank in range(world):
        p = os.path.join(directory, shard_meta(name, rank, world))
        if not os.path.exists(p):
            return None
        with open(p) as f:
            tables.append(json.load(f))
    canonical = tables[0]["leaves"]
    for t in tables[1:]:
        if t["leaves"] != canonical:
            raise CheckpointError(
                f"shard leaf tables disagree under {directory!r}; "
                "ranks snapshotted different trees")
    manifest = {"format": FORMAT, "name": name, "world": world,
                "step": step,
                "nbytes": int(sum(t["nbytes"] for t in tables)),
                "leaves": canonical}
    if extra:
        manifest["extra"] = dict(extra)
    payload = json.dumps(manifest).encode()
    _fsync_write(path, lambda f: f.write(payload), fsync=fsync)
    _fsync_dir(directory)
    return manifest


def read_manifest(directory: str, name: str = "state") -> dict:
    path = os.path.join(directory, manifest_file(name))
    if not os.path.exists(path):
        raise CheckpointNotCommitted(
            f"no committed manifest {manifest_file(name)!r} under "
            f"{directory!r} (crashed mid-persist, or not a checkpoint)")
    with open(path) as f:
        manifest = json.load(f)
    if manifest.get("format") != FORMAT:
        raise CheckpointError(
            f"unknown checkpoint format {manifest.get('format')!r}")
    return manifest


def wait_committed(directory: str, name: str, timeout: float) -> bool:
    """Poll (cheap stat) until the manifest lands; used by rank 0's
    persister to learn when the LAST rank's commit made the checkpoint
    real, without any cross-rank RPC."""
    import time

    deadline = time.monotonic() + timeout
    while True:
        if has_manifest(directory, name):
            return True
        if time.monotonic() >= deadline:
            return False
        time.sleep(0.02)
