"""Reshard-on-restore: manifest -> global leaves -> the CURRENT mesh.

Restore never assumes the saving topology: it reads the committed
manifest, reassembles each global leaf by concatenating its shard
slices (shard order is the shard index — bit-exact reassembly), and
hands the result back either whole (`restore_tree`) or re-sliced for a
NEW (rank, world) (`restore_shard`). An N-worker checkpoint therefore
restores onto M workers for any M: the new local shapes are derived
from the global shape alone, by the same rule the writer used.

Structure rebuild is path-based (zero-pickle): nested dicts and
lists/tuples come back from the encoded key paths directly; trees with
custom container nodes (optax states, dataclass pytrees) are rebuilt by
validating the manifest against a caller-provided `template` — the
"rebuild the same template locally, adopt the leaves" idiom the RLHF
placement switch already uses.
"""

from __future__ import annotations

import os
from typing import Any, List, Optional

from ray_tpu.checkpoint.manifest import (
    CheckpointError,
    encode_path,
    path_str,
    read_manifest,
    shard_axis_for,
    shard_npz,
)


class _ShardReader:
    """Lazy npz handles for one checkpoint's shard files."""

    def __init__(self, directory: str, name: str, world: int):
        import numpy as np

        self._np = np
        self.directory = directory
        self.name = name
        self.world = world
        self._files: dict = {}

    def _file(self, rank: int):
        f = self._files.get(rank)
        if f is None:
            path = os.path.join(self.directory,
                                shard_npz(self.name, rank, self.world))
            if not os.path.exists(path):
                raise CheckpointError(
                    f"manifest committed but shard file missing: {path}")
            f = self._files[rank] = self._np.load(path)
        return f

    def leaf(self, index: int, record: dict):
        """Reassemble leaf `index` to its GLOBAL value."""
        key = f"leaf_{index}"
        if record["shard_axis"] is None:
            return self._file(0)[key]
        parts = [self._file(r)[key] for r in range(self.world)]
        return self._np.concatenate(parts, axis=record["shard_axis"])

    def close(self):
        for f in self._files.values():
            try:
                f.close()
            except Exception:
                pass


def _global_leaves(directory: str, name: str) -> tuple:
    import numpy as np

    manifest = read_manifest(directory, name)
    reader = _ShardReader(directory, name, int(manifest["world"]))
    leaves = []
    try:
        for i, rec in enumerate(manifest["leaves"]):
            leaf = reader.leaf(i, rec)
            if list(leaf.shape) != list(rec["global_shape"]) \
                    or str(np.dtype(leaf.dtype)) != rec["dtype"]:
                raise CheckpointError(
                    f"leaf {path_str(rec['path'])}: reassembled "
                    f"{leaf.shape}/{leaf.dtype}, manifest says "
                    f"{rec['global_shape']}/{rec['dtype']}")
            leaves.append(np.array(leaf))  # own the memory past npz close
    finally:
        reader.close()
    return manifest, leaves


def _rebuild_from_paths(records: List[dict], leaves: List) -> Any:
    """Nested dict/list reconstruction from encoded paths. Containers
    that need a type registry (tuples come back as lists, namedtuples /
    custom nodes not at all) require a `template` instead."""
    if not records:
        return {}
    if not records[0]["path"]:
        if len(records) != 1:
            raise CheckpointError("multiple leaves at the tree root")
        return leaves[0]

    def build(items):  # items: [(remaining_path, leaf)]
        first = [p[0] for p, _ in items]
        if all("key" in seg for seg in first):
            out: dict = {}
            for (seg, *rest), leaf in ((tuple(p), l) for p, l in items):
                out.setdefault(seg["key"], []).append((list(rest), leaf))
            return {k: build(v) if v[0][0] else v[0][1]
                    for k, v in out.items()}
        if all("idx" in seg for seg in first):
            slots: dict = {}
            for p, leaf in items:
                slots.setdefault(p[0]["idx"], []).append((p[1:], leaf))
            if sorted(slots) != list(range(len(slots))):
                raise CheckpointError("non-contiguous sequence indices")
            return [build(v) if v[0][0] else v[0][1]
                    for _, v in sorted(slots.items())]
        raise CheckpointError(
            "checkpoint tree has attribute/custom container nodes; pass "
            "`template=` to restore into the original structure")

    return build([(list(r["path"]), l) for r, l in zip(records, leaves)])


def _unflatten_template(template: Any, records: List[dict],
                        leaves: List) -> Any:
    """Validate the manifest against `template`'s flatten order, then
    adopt the leaves into the template's structure."""
    import jax

    flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    if len(flat) != len(records):
        raise CheckpointError(
            f"template has {len(flat)} leaves, checkpoint {len(records)}")
    for (path, t_leaf), rec in zip(flat, records):
        enc = encode_path(path)
        if enc != rec["path"]:
            raise CheckpointError(
                f"template/checkpoint structure mismatch: "
                f"{path_str(enc)} vs {path_str(rec['path'])}")
        t_shape = tuple(int(d) for d in getattr(t_leaf, "shape", ()))
        if list(t_shape) != list(rec["global_shape"]):
            raise CheckpointError(
                f"leaf {path_str(enc)}: template shape {t_shape}, "
                f"checkpoint {tuple(rec['global_shape'])}")
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _maybe_device_put(leaves: List, device_put: bool) -> List:
    if not device_put:
        return leaves
    try:
        import jax

        return [jax.device_put(l) for l in leaves]
    except Exception:
        return leaves


def restore_tree(directory: str, *, name: str = "state",
                 template: Any = None, device_put: bool = False) -> Any:
    """Read the committed checkpoint under `directory` and return the
    FULL tree (global leaves), regardless of how many ranks saved it.
    With `device_put=True`, leaves land on the current default device."""
    manifest, leaves = _global_leaves(directory, name)
    leaves = _maybe_device_put(leaves, device_put)
    if template is not None:
        return _unflatten_template(template, manifest["leaves"], leaves)
    return _rebuild_from_paths(manifest["leaves"], leaves)


def restore_shard(directory: str, *, rank: int, world: int,
                  name: str = "state", template: Any = None,
                  device_put: bool = False) -> Any:
    """Restore onto the CURRENT topology: reassemble global leaves, then
    slice each for (`rank`, `world`) by the writer's sharding rule. The
    saving world size N and the restoring world size M are independent —
    this is the elastic `_RESIZE` restore path."""
    manifest, leaves = _global_leaves(directory, name)
    out = []
    for rec, leaf in zip(manifest["leaves"], leaves):
        axis = shard_axis_for(tuple(leaf.shape), world)
        if axis is None:
            out.append(leaf)
        else:
            per = leaf.shape[axis] // world
            out.append(leaf[rank * per:(rank + 1) * per].copy())
    out = _maybe_device_put(out, device_put)
    if template is not None:
        return _unflatten_template_sharded(template, manifest["leaves"], out)
    return _rebuild_from_paths(manifest["leaves"], out)


def _unflatten_template_sharded(template: Any, records: List[dict],
                                leaves: List) -> Any:
    """Template adoption for per-rank shards: template leaf shapes are
    the LOCAL shapes, so validate rank-local dims, not global ones."""
    import jax

    flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    if len(flat) != len(records):
        raise CheckpointError(
            f"template has {len(flat)} leaves, checkpoint {len(records)}")
    for (path, _), rec in zip(flat, records):
        enc = encode_path(path)
        if enc != rec["path"]:
            raise CheckpointError(
                f"template/checkpoint structure mismatch: "
                f"{path_str(enc)} vs {path_str(rec['path'])}")
    return jax.tree_util.tree_unflatten(treedef, leaves)
