"""Microsecond-scale snapshots: device -> pinned host buffers.

The train step's entire checkpoint stall is here: one explicit memcpy of
each local leaf (or this rank's slice of a replicated leaf) into a
preallocated host buffer. Serialization, fsync, manifest commit, and
replication all happen later on the plane's background thread against
the captured buffers, so mutating the live params after `snapshot_shard`
returns cannot corrupt the checkpoint (snapshot isolation).

Buffers come from a `BufferPool` keyed by (shape, dtype): steady-state
checkpointing reuses the same host memory every interval — no allocator
churn, pages stay faulted in, and on real TPU hosts the pool is where
pinned allocation would live. `np.asarray` on a CPU jax array can be a
zero-copy VIEW of the device buffer, which is exactly why the capture is
an explicit `np.copyto` into pool memory rather than a bare asarray: a
view would be mutated by the next optimizer step mid-persist.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.checkpoint import manifest as manifest_mod


class BufferPool:
    """Reusable host staging buffers, keyed by (shape, dtype).

    `acquire` hands out a free buffer or allocates one; `release` returns
    buffers after the background persist is done with them. Thread-safe:
    the train thread acquires while the persister releases."""

    def __init__(self):
        self._free: Dict[Tuple, List] = {}
        self._lock = threading.Lock()
        self.allocated = 0      # buffers ever allocated (reuse observability)
        self.acquired = 0

    def acquire(self, shape, dtype):
        import numpy as np

        key = (tuple(shape), str(np.dtype(dtype)))
        with self._lock:
            self.acquired += 1
            free = self._free.get(key)
            if free:
                return free.pop()
            self.allocated += 1
        return np.empty(shape, dtype=dtype)

    def release(self, buffers) -> None:
        import numpy as np

        with self._lock:
            for buf in buffers:
                key = (tuple(buf.shape), str(np.dtype(buf.dtype)))
                self._free.setdefault(key, []).append(buf)


class Snapshot:
    """A captured shard: host buffers + the leaf table that describes
    them. `leaves[i]` is None for leaves this rank does not store
    (replicated leaves on rank > 0)."""

    def __init__(self, name: str, rank: int, world: int,
                 records: List[dict], leaves: List, nbytes: int,
                 snapshot_ms: float, pool: Optional[BufferPool]):
        self.name = name
        self.rank = rank
        self.world = world
        self.records = records
        self.leaves = leaves
        self.nbytes = nbytes
        self.snapshot_ms = snapshot_ms
        self._pool = pool

    def release(self) -> None:
        """Return the staging buffers to the pool (persister-side, after
        the shard file is durable)."""
        if self._pool is not None:
            self._pool.release([l for l in self.leaves if l is not None])
            self._pool = None


def _local_slice(leaf, axis: Optional[int], rank: int, world: int):
    """This rank's piece of a leaf: an axis-0 slice when sharded, the
    whole leaf on rank 0 when replicated, None otherwise."""
    if axis is None:
        return leaf if rank == 0 else None
    per = leaf.shape[axis] // world
    return leaf[rank * per:(rank + 1) * per]


def snapshot_shard(tree: Any, *, rank: int = 0, world: int = 1,
                   name: str = "state",
                   pool: Optional[BufferPool] = None) -> Snapshot:
    """Capture this rank's shard of `tree` into host buffers and return.

    Leaves with a leading dim divisible by `world` are sliced (each rank
    captures 1/world of the bytes — the DDP/replicated-params case);
    everything else is captured whole by rank 0 only. The device->host
    copies are started async first (TPU: overlapped DMA) and then landed
    into pool buffers with one memcpy each.
    """
    import numpy as np

    t0 = time.perf_counter()
    records, leaves = manifest_mod.leaf_records(tree, world)
    slices = [_local_slice(leaf, rec["shard_axis"], rank, world)
              for rec, leaf in zip(records, leaves)]
    # Kick off all device->host transfers before landing any of them —
    # on accelerator backends the copies overlap; on CPU it's a no-op.
    for s in slices:
        if s is not None and hasattr(s, "copy_to_host_async"):
            try:
                s.copy_to_host_async()
            except Exception:
                pass
    captured: List = []
    nbytes = 0
    for s in slices:
        if s is None:
            captured.append(None)
            continue
        src = np.asarray(s)
        if pool is not None:
            buf = pool.acquire(src.shape, src.dtype)
        else:
            buf = np.empty(src.shape, dtype=src.dtype)
        np.copyto(buf, src)
        captured.append(buf)
        nbytes += buf.nbytes
    return Snapshot(name, rank, world, records, captured, nbytes,
                    (time.perf_counter() - t0) * 1e3, pool)
