// XValue: the cross-language value vocabulary of the ray_tpu wire.
//
// Byte-exact mirror of ray_tpu/runtime/xlang.py (tags, little-endian
// layout). Dynamically typed variant; ndarrays carry dtype string +
// dims + raw buffer and map to numpy on the Python side.
#pragma once

#include <cstdint>
#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

#include "crypto.hpp"  // Bytes

namespace raytpu {

class XValue;
using XList = std::vector<XValue>;
using XDict = std::map<std::string, XValue>;

struct XArray {
  std::string dtype;           // numpy dtype str, e.g. "<f4"
  std::vector<uint64_t> dims;  // C-order
  Bytes data;
};

class XValue {
 public:
  enum class Tag : uint8_t {
    None = 0, False_ = 1, True_ = 2, Int = 3, Float = 4,
    Str = 5, Binary = 6, List = 7, Dict = 8, NdArray = 9,
  };

  XValue() : tag_(Tag::None) {}
  XValue(std::nullptr_t) : tag_(Tag::None) {}
  XValue(bool b) : tag_(b ? Tag::True_ : Tag::False_) {}
  XValue(int64_t i) : tag_(Tag::Int), i_(i) {}
  XValue(int i) : tag_(Tag::Int), i_(i) {}
  XValue(double d) : tag_(Tag::Float), f_(d) {}
  XValue(const char* s) : tag_(Tag::Str), s_(s) {}
  XValue(std::string s) : tag_(Tag::Str), s_(std::move(s)) {}
  XValue(Bytes b) : tag_(Tag::Binary), b_(std::move(b)) {}
  XValue(XList l) : tag_(Tag::List), list_(std::make_shared<XList>(std::move(l))) {}
  XValue(XDict d) : tag_(Tag::Dict), dict_(std::make_shared<XDict>(std::move(d))) {}
  XValue(XArray a) : tag_(Tag::NdArray), arr_(std::make_shared<XArray>(std::move(a))) {}

  Tag tag() const { return tag_; }
  bool is_none() const { return tag_ == Tag::None; }
  bool is_error_dict() const {
    return tag_ == Tag::Dict && dict_->count("error");
  }

  bool as_bool() const {
    check(tag_ == Tag::True_ || tag_ == Tag::False_, "bool");
    return tag_ == Tag::True_;
  }
  int64_t as_int() const { check(tag_ == Tag::Int, "int"); return i_; }
  double as_float() const {
    if (tag_ == Tag::Int) return double(i_);
    check(tag_ == Tag::Float, "float");
    return f_;
  }
  const std::string& as_str() const { check(tag_ == Tag::Str, "str"); return s_; }
  const Bytes& as_bytes() const { check(tag_ == Tag::Binary, "bytes"); return b_; }
  const XList& as_list() const { check(tag_ == Tag::List, "list"); return *list_; }
  const XDict& as_dict() const { check(tag_ == Tag::Dict, "dict"); return *dict_; }
  const XArray& as_array() const { check(tag_ == Tag::NdArray, "ndarray"); return *arr_; }

  const XValue& at(const std::string& key) const {
    const auto& d = as_dict();
    auto it = d.find(key);
    if (it == d.end()) throw std::out_of_range("no key: " + key);
    return it->second;
  }

  void encode(Bytes& out) const;
  static XValue decode(const Bytes& buf, size_t& pos);

  std::string repr() const;  // stable text rendering (CLI output)

 private:
  void check(bool ok, const char* want) const {
    if (!ok) throw std::runtime_error(std::string("xvalue is not ") + want);
  }

  Tag tag_;
  int64_t i_ = 0;
  double f_ = 0;
  std::string s_;
  Bytes b_;
  std::shared_ptr<XList> list_;
  std::shared_ptr<XDict> dict_;
  std::shared_ptr<XArray> arr_;
};

// Envelope (body of one RTX frame).
struct Envelope {
  uint8_t kind;
  bool has_msg_id;
  uint64_t msg_id;
  std::string method;
  XValue data;

  Bytes encode() const;
  static Envelope decode(const Bytes& body);
};

constexpr uint8_t KIND_REQUEST = 0, KIND_REPLY = 1, KIND_ERROR = 2,
                  KIND_PUSH = 3;

}  // namespace raytpu
