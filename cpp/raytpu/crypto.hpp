// Crypto primitives for the ray_tpu cross-language wire.
//
// The wire handshake needs HMAC-SHA256 (challenge proofs + MAC-key
// derivation) and keyed BLAKE2b-128 (per-frame MACs) — see
// ray_tpu/runtime/rpc.py for the protocol. The toolchain image ships no
// OpenSSL headers, so both algorithms are implemented here from their
// public specifications (FIPS 180-4 and RFC 7693). Small, dependency-free,
// and covered by test vectors cross-checked against Python hashlib in
// tests/test_xlang_cpp.py.
#pragma once

#include <cstddef>
#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

namespace raytpu {

using Bytes = std::vector<uint8_t>;

// ------------------------------------------------------------- SHA-256

struct Sha256 {
  uint32_t h[8];
  uint64_t len = 0;
  uint8_t buf[64];
  size_t buflen = 0;

  Sha256();
  void update(const uint8_t* data, size_t n);
  void update(const Bytes& b) { update(b.data(), b.size()); }
  Bytes digest();  // finalizes; throws if called twice (state is consumed)

 private:
  bool finalized = false;
  void compress(const uint8_t* block);
};

Bytes sha256(const Bytes& data);
Bytes hmac_sha256(const Bytes& key, const Bytes& msg);

// ------------------------------------------------- BLAKE2b (RFC 7693)

// Keyed, sequential mode, configurable digest size (wire uses 16).
struct Blake2b {
  uint64_t h[8];
  uint64_t t = 0;       // bytes compressed so far
  uint8_t buf[128];
  size_t buflen = 0;
  size_t outlen;

  explicit Blake2b(size_t digest_size, const Bytes& key = {});
  void update(const uint8_t* data, size_t n);
  void update(const Bytes& b) { update(b.data(), b.size()); }
  Bytes digest();  // finalizes; throws if called twice (state is consumed)

 private:
  bool finalized = false;
  void compress(const uint8_t* block, bool last);
};

Bytes blake2b(const Bytes& data, size_t digest_size, const Bytes& key = {});

// ------------------------------------------------------------- helpers

Bytes from_hex(const std::string& hex);
std::string to_hex(const Bytes& b);
bool const_time_eq(const Bytes& a, const Bytes& b);

}  // namespace raytpu
