// C++ task HOSTING: a worker that registers named C++ functions with the
// cluster, pulls tasks over the authenticated RTX wire, executes them
// natively, and pushes results back. The Python driver submits with
// cross_language.hosted("name").remote(...) and gets a real ObjectRef.
//
// Reference analog: the C++ task executor of harborn/ray
// (cpp/src/ray/runtime/task/task_executor.cc:1) — tasks address functions
// by DESCRIPTOR (name), args/results are language-neutral values.
// Transport is long-poll against the client proxy's xworker_* handlers
// (ray_tpu/util/client/server.py) rather than a raylet push: same task
// frames, pull-driven.
#pragma once

#include <functional>
#include <map>
#include <string>

#include "client.hpp"

namespace raytpu {

class Worker {
 public:
  using Fn = std::function<XValue(const XList&)>;

  Worker(Client& client, std::string name)
      : client_(client), name_(std::move(name)) {}

  void register_fn(const std::string& fn_name, Fn fn) {
    fns_[fn_name] = std::move(fn);
  }

  // Announce this worker + its function names to the cluster
  // (xworker_register). Must be called before serve().
  void register_with_cluster();

  // Pull/execute/reply until `max_tasks` tasks served (0 = unlimited) or,
  // with idle_exit, until one poll comes back empty. Returns tasks served.
  size_t serve(size_t max_tasks, bool idle_exit, double poll_timeout_s);

  // Graceful goodbye (xworker_unregister): queued tasks are failed over
  // to the submitter instead of hanging.
  void unregister();

  const Bytes& worker_id() const { return worker_id_; }

 private:
  Client& client_;
  std::string name_;
  Bytes worker_id_;
  std::map<std::string, Fn> fns_;
};

}  // namespace raytpu
