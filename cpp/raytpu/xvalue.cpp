#include "xvalue.hpp"

#include <cstring>
#include <sstream>

namespace raytpu {

static void put_u16(Bytes& out, uint16_t v) {
  out.push_back(uint8_t(v));
  out.push_back(uint8_t(v >> 8));
}

static void put_u32(Bytes& out, uint32_t v) {
  for (int i = 0; i < 4; i++) out.push_back(uint8_t(v >> (8 * i)));
}

static void put_u64(Bytes& out, uint64_t v) {
  for (int i = 0; i < 8; i++) out.push_back(uint8_t(v >> (8 * i)));
}

static uint32_t get_u32(const Bytes& buf, size_t& pos) {
  if (pos + 4 > buf.size()) throw std::runtime_error("xvalue: truncated u32");
  uint32_t v = 0;
  for (int i = 3; i >= 0; i--) v = (v << 8) | buf[pos + i];
  pos += 4;
  return v;
}

static uint64_t get_u64(const Bytes& buf, size_t& pos) {
  if (pos + 8 > buf.size()) throw std::runtime_error("xvalue: truncated u64");
  uint64_t v = 0;
  for (int i = 7; i >= 0; i--) v = (v << 8) | buf[pos + i];
  pos += 8;
  return v;
}

static void need(const Bytes& buf, size_t pos, size_t n) {
  // pos + n can wrap for peer-controlled n; compare without the addition.
  if (pos > buf.size() || n > buf.size() - pos)
    throw std::runtime_error("xvalue: truncated");
}

void XValue::encode(Bytes& out) const {
  out.push_back(uint8_t(tag_));
  switch (tag_) {
    case Tag::None:
    case Tag::False_:
    case Tag::True_:
      break;
    case Tag::Int:
      put_u64(out, uint64_t(i_));
      break;
    case Tag::Float: {
      uint64_t bits;
      std::memcpy(&bits, &f_, 8);
      put_u64(out, bits);
      break;
    }
    case Tag::Str:
      put_u32(out, uint32_t(s_.size()));
      out.insert(out.end(), s_.begin(), s_.end());
      break;
    case Tag::Binary:
      put_u32(out, uint32_t(b_.size()));
      out.insert(out.end(), b_.begin(), b_.end());
      break;
    case Tag::List:
      put_u32(out, uint32_t(list_->size()));
      for (const auto& v : *list_) v.encode(out);
      break;
    case Tag::Dict:
      put_u32(out, uint32_t(dict_->size()));
      for (const auto& [k, v] : *dict_) {
        put_u32(out, uint32_t(k.size()));
        out.insert(out.end(), k.begin(), k.end());
        v.encode(out);
      }
      break;
    case Tag::NdArray: {
      const XArray& a = *arr_;
      if (a.dtype.size() > 255) throw std::runtime_error("dtype too long");
      out.push_back(uint8_t(a.dtype.size()));
      out.insert(out.end(), a.dtype.begin(), a.dtype.end());
      out.push_back(uint8_t(a.dims.size()));
      for (uint64_t d : a.dims) put_u64(out, d);
      out.insert(out.end(), a.data.begin(), a.data.end());
      break;
    }
  }
}

XValue XValue::decode(const Bytes& buf, size_t& pos) {
  need(buf, pos, 1);
  uint8_t tag = buf[pos++];
  switch (Tag(tag)) {
    case Tag::None:
      return XValue();
    case Tag::False_:
      return XValue(false);
    case Tag::True_:
      return XValue(true);
    case Tag::Int:
      return XValue(int64_t(get_u64(buf, pos)));
    case Tag::Float: {
      uint64_t bits = get_u64(buf, pos);
      double d;
      std::memcpy(&d, &bits, 8);
      return XValue(d);
    }
    case Tag::Str: {
      uint32_t n = get_u32(buf, pos);
      need(buf, pos, n);
      std::string s(buf.begin() + pos, buf.begin() + pos + n);
      pos += n;
      return XValue(std::move(s));
    }
    case Tag::Binary: {
      uint32_t n = get_u32(buf, pos);
      need(buf, pos, n);
      Bytes b(buf.begin() + pos, buf.begin() + pos + n);
      pos += n;
      return XValue(std::move(b));
    }
    case Tag::List: {
      uint32_t n = get_u32(buf, pos);
      XList l;
      l.reserve(n);
      for (uint32_t i = 0; i < n; i++) l.push_back(decode(buf, pos));
      return XValue(std::move(l));
    }
    case Tag::Dict: {
      uint32_t n = get_u32(buf, pos);
      XDict d;
      for (uint32_t i = 0; i < n; i++) {
        uint32_t kl = get_u32(buf, pos);
        need(buf, pos, kl);
        std::string k(buf.begin() + pos, buf.begin() + pos + kl);
        pos += kl;
        d.emplace(std::move(k), decode(buf, pos));
      }
      return XValue(std::move(d));
    }
    case Tag::NdArray: {
      need(buf, pos, 1);
      uint8_t dl = buf[pos++];
      need(buf, pos, dl);
      XArray a;
      a.dtype.assign(buf.begin() + pos, buf.begin() + pos + dl);
      pos += dl;
      need(buf, pos, 1);
      uint8_t ndim = buf[pos++];
      uint64_t count = 1;
      for (uint8_t i = 0; i < ndim; i++) {
        a.dims.push_back(get_u64(buf, pos));
        // Peer-controlled dims: reject overflow rather than wrapping to a
        // small count that would pass the bounds check below.
        if (__builtin_mul_overflow(count, a.dims.back(), &count))
          throw std::runtime_error("xvalue: ndarray size overflow");
      }
      // itemsize = trailing digits of the dtype str ("<f4" -> 4).
      size_t isz = 0;
      for (char c : a.dtype)
        if (c >= '0' && c <= '9') isz = isz * 10 + size_t(c - '0');
      if (isz == 0) throw std::runtime_error("bad dtype: " + a.dtype);
      uint64_t nbytes;
      if (__builtin_mul_overflow(count, isz, &nbytes))
        throw std::runtime_error("xvalue: ndarray size overflow");
      need(buf, pos, nbytes);
      a.data.assign(buf.begin() + pos, buf.begin() + pos + nbytes);
      pos += nbytes;
      return XValue(std::move(a));
    }
  }
  throw std::runtime_error("xvalue: unknown tag " + std::to_string(tag));
}

std::string XValue::repr() const {
  std::ostringstream os;
  switch (tag_) {
    case Tag::None: os << "null"; break;
    case Tag::False_: os << "false"; break;
    case Tag::True_: os << "true"; break;
    case Tag::Int: os << i_; break;
    case Tag::Float: os << f_; break;
    case Tag::Str: os << '"' << s_ << '"'; break;
    case Tag::Binary: os << "b:" << to_hex(b_); break;
    case Tag::List: {
      os << '[';
      bool first = true;
      for (const auto& v : *list_) {
        if (!first) os << ", ";
        first = false;
        os << v.repr();
      }
      os << ']';
      break;
    }
    case Tag::Dict: {
      os << '{';
      bool first = true;
      for (const auto& [k, v] : *dict_) {
        if (!first) os << ", ";
        first = false;
        os << '"' << k << "\": " << v.repr();
      }
      os << '}';
      break;
    }
    case Tag::NdArray: {
      os << "ndarray(" << arr_->dtype << ", [";
      for (size_t i = 0; i < arr_->dims.size(); i++)
        os << (i ? "," : "") << arr_->dims[i];
      os << "], " << arr_->data.size() << "B)";
      break;
    }
  }
  return os.str();
}

// ------------------------------------------------------------- envelope

Bytes Envelope::encode() const {
  Bytes out;
  out.push_back(kind);
  out.push_back(has_msg_id ? 1 : 0);
  put_u64(out, has_msg_id ? msg_id : 0);
  put_u16(out, uint16_t(method.size()));
  out.insert(out.end(), method.begin(), method.end());
  data.encode(out);
  return out;
}

Envelope Envelope::decode(const Bytes& body) {
  if (body.size() < 12) throw std::runtime_error("envelope: truncated");
  Envelope e;
  e.kind = body[0];
  e.has_msg_id = body[1] != 0;
  size_t pos = 2;
  e.msg_id = get_u64(body, pos);
  need(body, pos, 2);
  uint16_t ml = uint16_t(body[pos]) | (uint16_t(body[pos + 1]) << 8);
  pos += 2;
  need(body, pos, ml);
  e.method.assign(body.begin() + pos, body.begin() + pos + ml);
  pos += ml;
  e.data = XValue::decode(body, pos);
  if (pos != body.size()) throw std::runtime_error("envelope: trailing bytes");
  return e;
}

}  // namespace raytpu
