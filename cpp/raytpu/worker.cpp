#include "worker.hpp"

#include <stdexcept>

namespace raytpu {

void Worker::register_with_cluster() {
  XList names;
  for (const auto& kv : fns_) names.push_back(XValue(kv.first));
  XDict args;
  args.emplace("name", XValue(name_));
  args.emplace("functions", XValue(std::move(names)));
  XValue reply = client_.call("xworker_register", std::move(args));
  worker_id_ = reply.at("worker_id").as_bytes();
}

void Worker::unregister() {
  if (worker_id_.empty()) return;
  XDict args;
  args.emplace("worker_id", XValue(worker_id_));
  client_.call("xworker_unregister", std::move(args));
  worker_id_.clear();
}

size_t Worker::serve(size_t max_tasks, bool idle_exit,
                     double poll_timeout_s) {
  if (worker_id_.empty())
    throw std::logic_error("serve() before register_with_cluster()");
  size_t served = 0;
  int reregisters = 0;
  for (;;) {
    XDict poll;
    poll.emplace("worker_id", XValue(worker_id_));
    poll.emplace("timeout_s", XValue(poll_timeout_s));
    XValue task;
    try {
      task = client_.call("xworker_poll", std::move(poll));
    } catch (const std::exception& e) {
      // The proxy answers an unknown worker id with an error telling us
      // to re-register (its session state restarted/reaped). Do so a
      // bounded number of times instead of dying without unregister().
      if (std::string(e.what()).find("re-register") != std::string::npos &&
          reregisters < 3) {
        reregisters++;
        register_with_cluster();
        continue;
      }
      throw;
    }
    const XDict& t = task.as_dict();
    if (t.count("idle")) {
      if (idle_exit) break;
      continue;
    }
    const Bytes& task_id = task.at("task_id").as_bytes();
    const std::string& fn_name = task.at("fn").as_str();
    XDict result;
    result.emplace("worker_id", XValue(worker_id_));
    result.emplace("task_id", XValue(task_id));
    try {
      // Args travel as one encoded XValue list (validated against the
      // xlang vocabulary at submit time on the Python side).
      XList fn_args;
      auto it = t.find("args");
      if (it != t.end() && it->second.tag() == XValue::Tag::Binary) {
        size_t pos = 0;
        fn_args = XValue::decode(it->second.as_bytes(), pos).as_list();
      } else if (it != t.end() && it->second.tag() == XValue::Tag::List) {
        fn_args = it->second.as_list();
      }
      auto fn = fns_.find(fn_name);
      if (fn == fns_.end())
        throw std::runtime_error("no such function: " + fn_name);
      XValue out = fn->second(fn_args);
      result.emplace("status", XValue(std::string("ok")));
      result.emplace("value", std::move(out));
    } catch (const std::exception& e) {
      result.emplace("status", XValue(std::string("error")));
      result.emplace("error", XValue(std::string(e.what())));
    }
    client_.call("xworker_result", std::move(result));
    served++;
    if (max_tasks && served >= max_tasks) break;
  }
  return served;
}

}  // namespace raytpu
