#include "crypto.hpp"

#include <stdexcept>

namespace raytpu {

// ------------------------------------------------------------- SHA-256
// FIPS 180-4. Round constants = frac(cbrt(first 64 primes)).

static const uint32_t K256[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

static inline uint32_t rotr32(uint32_t x, int n) {
  return (x >> n) | (x << (32 - n));
}

Sha256::Sha256() {
  static const uint32_t H0[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372,
                                 0xa54ff53a, 0x510e527f, 0x9b05688c,
                                 0x1f83d9ab, 0x5be0cd19};
  std::memcpy(h, H0, sizeof(h));
}

void Sha256::compress(const uint8_t* p) {
  uint32_t w[64];
  for (int i = 0; i < 16; i++)
    w[i] = (uint32_t(p[4 * i]) << 24) | (uint32_t(p[4 * i + 1]) << 16) |
           (uint32_t(p[4 * i + 2]) << 8) | uint32_t(p[4 * i + 3]);
  for (int i = 16; i < 64; i++) {
    uint32_t s0 = rotr32(w[i - 15], 7) ^ rotr32(w[i - 15], 18) ^ (w[i - 15] >> 3);
    uint32_t s1 = rotr32(w[i - 2], 17) ^ rotr32(w[i - 2], 19) ^ (w[i - 2] >> 10);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint32_t a = h[0], b = h[1], c = h[2], d = h[3];
  uint32_t e = h[4], f = h[5], g = h[6], hh = h[7];
  for (int i = 0; i < 64; i++) {
    uint32_t S1 = rotr32(e, 6) ^ rotr32(e, 11) ^ rotr32(e, 25);
    uint32_t ch = (e & f) ^ (~e & g);
    uint32_t t1 = hh + S1 + ch + K256[i] + w[i];
    uint32_t S0 = rotr32(a, 2) ^ rotr32(a, 13) ^ rotr32(a, 22);
    uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
    uint32_t t2 = S0 + maj;
    hh = g; g = f; f = e; e = d + t1;
    d = c; c = b; b = a; a = t1 + t2;
  }
  h[0] += a; h[1] += b; h[2] += c; h[3] += d;
  h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
}

void Sha256::update(const uint8_t* data, size_t n) {
  len += n;
  while (n > 0) {
    size_t take = 64 - buflen;
    if (take > n) take = n;
    std::memcpy(buf + buflen, data, take);
    buflen += take;
    data += take;
    n -= take;
    if (buflen == 64) {
      compress(buf);
      buflen = 0;
    }
  }
}

Bytes Sha256::digest() {
  if (finalized)
    throw std::logic_error("Sha256::digest() called twice; state is consumed");
  finalized = true;
  uint64_t bitlen = len * 8;
  uint8_t pad = 0x80;
  update(&pad, 1);
  uint8_t zero = 0;
  while (buflen != 56) update(&zero, 1);
  uint8_t lenbuf[8];
  for (int i = 0; i < 8; i++) lenbuf[i] = uint8_t(bitlen >> (56 - 8 * i));
  // update() would re-count these; feed the final block directly.
  std::memcpy(buf + 56, lenbuf, 8);
  compress(buf);
  Bytes out(32);
  for (int i = 0; i < 8; i++) {
    out[4 * i] = uint8_t(h[i] >> 24);
    out[4 * i + 1] = uint8_t(h[i] >> 16);
    out[4 * i + 2] = uint8_t(h[i] >> 8);
    out[4 * i + 3] = uint8_t(h[i]);
  }
  return out;
}

Bytes sha256(const Bytes& data) {
  Sha256 s;
  s.update(data);
  return s.digest();
}

Bytes hmac_sha256(const Bytes& key, const Bytes& msg) {
  Bytes k = key;
  if (k.size() > 64) k = sha256(k);
  k.resize(64, 0);
  Bytes ipad(64), opad(64);
  for (int i = 0; i < 64; i++) {
    ipad[i] = k[i] ^ 0x36;
    opad[i] = k[i] ^ 0x5c;
  }
  Sha256 inner;
  inner.update(ipad);
  inner.update(msg);
  Bytes ih = inner.digest();
  Sha256 outer;
  outer.update(opad);
  outer.update(ih);
  return outer.digest();
}

// ------------------------------------------------- BLAKE2b (RFC 7693)

static const uint64_t B2B_IV[8] = {
    0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL, 0x3c6ef372fe94f82bULL,
    0xa54ff53a5f1d36f1ULL, 0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
    0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL};

static const uint8_t B2B_SIGMA[12][16] = {
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3},
    {11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4},
    {7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8},
    {9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13},
    {2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9},
    {12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11},
    {13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10},
    {6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5},
    {10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0},
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3}};

static inline uint64_t rotr64(uint64_t x, int n) {
  return (x >> n) | (x << (64 - n));
}

static inline uint64_t load64(const uint8_t* p) {
  uint64_t v = 0;
  for (int i = 7; i >= 0; i--) v = (v << 8) | p[i];
  return v;
}

Blake2b::Blake2b(size_t digest_size, const Bytes& key) : outlen(digest_size) {
  if (digest_size == 0 || digest_size > 64)
    throw std::invalid_argument("blake2b digest_size must be 1..64");
  if (key.size() > 64) throw std::invalid_argument("blake2b key too long");
  for (int i = 0; i < 8; i++) h[i] = B2B_IV[i];
  // Parameter block word 0: depth=1, fanout=1, key length, digest length.
  h[0] ^= 0x01010000ULL ^ (uint64_t(key.size()) << 8) ^ uint64_t(digest_size);
  if (!key.empty()) {
    uint8_t block[128] = {0};
    std::memcpy(block, key.data(), key.size());
    update(block, 128);
  }
}

void Blake2b::compress(const uint8_t* block, bool last) {
  uint64_t m[16], v[16];
  for (int i = 0; i < 16; i++) m[i] = load64(block + 8 * i);
  for (int i = 0; i < 8; i++) {
    v[i] = h[i];
    v[i + 8] = B2B_IV[i];
  }
  v[12] ^= t;       // low word of the byte counter (high word unused <2^64)
  if (last) v[14] = ~v[14];
#define B2B_G(a, b, c, d, x, y)              \
  do {                                       \
    v[a] = v[a] + v[b] + (x);                \
    v[d] = rotr64(v[d] ^ v[a], 32);          \
    v[c] = v[c] + v[d];                      \
    v[b] = rotr64(v[b] ^ v[c], 24);          \
    v[a] = v[a] + v[b] + (y);                \
    v[d] = rotr64(v[d] ^ v[a], 16);          \
    v[c] = v[c] + v[d];                      \
    v[b] = rotr64(v[b] ^ v[c], 63);          \
  } while (0)
  for (int r = 0; r < 12; r++) {
    const uint8_t* s = B2B_SIGMA[r];
    B2B_G(0, 4, 8, 12, m[s[0]], m[s[1]]);
    B2B_G(1, 5, 9, 13, m[s[2]], m[s[3]]);
    B2B_G(2, 6, 10, 14, m[s[4]], m[s[5]]);
    B2B_G(3, 7, 11, 15, m[s[6]], m[s[7]]);
    B2B_G(0, 5, 10, 15, m[s[8]], m[s[9]]);
    B2B_G(1, 6, 11, 12, m[s[10]], m[s[11]]);
    B2B_G(2, 7, 8, 13, m[s[12]], m[s[13]]);
    B2B_G(3, 4, 9, 14, m[s[14]], m[s[15]]);
  }
#undef B2B_G
  for (int i = 0; i < 8; i++) h[i] ^= v[i] ^ v[i + 8];
}

void Blake2b::update(const uint8_t* data, size_t n) {
  while (n > 0) {
    if (buflen == 128) {
      // Buffer full AND more input coming: this is a non-final block.
      t += 128;
      compress(buf, false);
      buflen = 0;
    }
    size_t take = 128 - buflen;
    if (take > n) take = n;
    std::memcpy(buf + buflen, data, take);
    buflen += take;
    data += take;
    n -= take;
  }
}

Bytes Blake2b::digest() {
  if (finalized)
    throw std::logic_error("Blake2b::digest() called twice; state is consumed");
  finalized = true;
  // Final block: pad with zeros, counter counts only real bytes.
  t += buflen;
  std::memset(buf + buflen, 0, 128 - buflen);
  compress(buf, true);
  Bytes out(outlen);
  for (size_t i = 0; i < outlen; i++)
    out[i] = uint8_t(h[i / 8] >> (8 * (i % 8)));
  return out;
}

Bytes blake2b(const Bytes& data, size_t digest_size, const Bytes& key) {
  Blake2b b(digest_size, key);
  b.update(data);
  return b.digest();
}

// ------------------------------------------------------------- helpers

Bytes from_hex(const std::string& hex) {
  if (hex.size() % 2) throw std::invalid_argument("odd hex length");
  auto nib = [](char c) -> int {
    if (c >= '0' && c <= '9') return c - '0';
    if (c >= 'a' && c <= 'f') return c - 'a' + 10;
    if (c >= 'A' && c <= 'F') return c - 'A' + 10;
    throw std::invalid_argument("bad hex digit");
  };
  Bytes out(hex.size() / 2);
  for (size_t i = 0; i < out.size(); i++)
    out[i] = uint8_t((nib(hex[2 * i]) << 4) | nib(hex[2 * i + 1]));
  return out;
}

std::string to_hex(const Bytes& b) {
  static const char* d = "0123456789abcdef";
  std::string out;
  out.reserve(b.size() * 2);
  for (uint8_t c : b) {
    out.push_back(d[c >> 4]);
    out.push_back(d[c & 15]);
  }
  return out;
}

bool const_time_eq(const Bytes& a, const Bytes& b) {
  if (a.size() != b.size()) return false;
  uint8_t acc = 0;
  for (size_t i = 0; i < a.size(); i++) acc |= a[i] ^ b[i];
  return acc == 0;
}

}  // namespace raytpu
