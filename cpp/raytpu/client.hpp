// Native C++ client for ray_tpu clusters (the N31 language binding).
//
// Speaks the cross-language wire dialect end-to-end:
//   * TCP framing + mutual HMAC-SHA256 handshake + per-frame keyed
//     BLAKE2b-128 MACs (protocol: ray_tpu/runtime/rpc.py);
//   * RTX envelopes carrying XValue payloads (ray_tpu/runtime/xlang.py);
//   * cluster ops against the ClientProxyServer's xlang handlers
//     (ray_tpu/util/client/server.py): call-by-name tasks, put/get/wait,
//     named actors, GCS KV.
//
// Reference analog: the C++ worker/client of harborn/ray
// (src/ray/core_worker C++ bindings + python/ray/cross_language.py) —
// calls into another language go by function NAME with language-neutral
// values, never pickled closures.
#pragma once

#include <optional>
#include <string>

#include "xvalue.hpp"

namespace raytpu {

class Client {
 public:
  // token_hex: the cluster session token (hex, from the session dir's
  // auth_token file or RAY_TPU_AUTH_TOKEN). Empty = unauthenticated wire.
  Client(const std::string& host, uint16_t port,
         const std::string& token_hex = "", double timeout_s = 30.0);
  ~Client();
  Client(const Client&) = delete;
  Client& operator=(const Client&) = delete;

  // Generic RPC: send one request, wait for its reply. Throws
  // std::runtime_error on wire/auth errors or KIND_ERROR replies and on
  // {"error": ...} reply dicts.
  XValue call(const std::string& method, XDict args);

  // -- convenience ops over the xlang proxy handlers -------------------
  XValue hello();
  Bytes put(XValue value);
  XValue get(const Bytes& ref, double timeout_s = 60.0);
  // Submit a named/importable Python function; returns the object ref.
  Bytes submit(const std::string& fn_name, XList args = {},
               XDict kwargs = {});
  Bytes actor_get(const std::string& name);
  Bytes actor_call(const Bytes& actor_id, const std::string& method,
                   XList args = {}, XDict kwargs = {});
  void kv_put(const std::string& key, const Bytes& value);
  std::optional<Bytes> kv_get(const std::string& key);
  void release(const Bytes& ref);

  // Wrap a ref for use inside submit() args ({"$ref": ref}).
  static XValue ref_arg(const Bytes& ref);

  void close();

 private:
  void handshake(const Bytes& token);
  void send_frame(const Bytes& body);
  Bytes recv_frame();
  void write_all(const uint8_t* p, size_t n);
  void read_all(uint8_t* p, size_t n);

  int fd_ = -1;
  uint64_t next_msg_id_ = 0;
  uint64_t send_seq_ = 0, recv_seq_ = 0;
  Bytes mac_key_;  // empty = MAC off
};

}  // namespace raytpu
