#include "client.hpp"

#include <arpa/inet.h>
#include <netdb.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

#include <cstring>
#include <random>
#include <stdexcept>

namespace raytpu {

static const uint8_t PROTOCOL_VERSION = 1;
static const size_t CHALLENGE = 32;
static const size_t MAC_SIZE = 16;

static Bytes magic(const char* m3) {
  Bytes b(m3, m3 + 3);
  b.push_back(PROTOCOL_VERSION);
  return b;
}

Client::Client(const std::string& host, uint16_t port,
               const std::string& token_hex, double timeout_s) {
  struct addrinfo hints {};
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  int rc = ::getaddrinfo(host.c_str(), std::to_string(port).c_str(), &hints,
                         &res);
  if (rc != 0)
    throw std::runtime_error(std::string("resolve failed: ") +
                             gai_strerror(rc));
  int fd = -1;
  for (auto* ai = res; ai; ai = ai->ai_next) {
    fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    if (::connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
    ::close(fd);
    fd = -1;
  }
  ::freeaddrinfo(res);
  if (fd < 0) throw std::runtime_error("connect failed to " + host);
  struct timeval tv;
  tv.tv_sec = long(timeout_s);
  tv.tv_usec = long((timeout_s - double(tv.tv_sec)) * 1e6);
  ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
  fd_ = fd;
  if (!token_hex.empty()) handshake(from_hex(token_hex));
}

Client::~Client() { close(); }

void Client::close() {
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
}

void Client::write_all(const uint8_t* p, size_t n) {
  while (n > 0) {
    ssize_t w = ::send(fd_, p, n, 0);
    if (w <= 0) throw std::runtime_error("send failed");
    p += w;
    n -= size_t(w);
  }
}

void Client::read_all(uint8_t* p, size_t n) {
  while (n > 0) {
    ssize_t r = ::recv(fd_, p, n, 0);
    if (r <= 0) throw std::runtime_error("connection closed");
    p += r;
    n -= size_t(r);
  }
}

void Client::handshake(const Bytes& token) {
  // server -> RTA+ver + sc ; client -> cc + HMAC(token,"c"+sc+cc) ;
  // server -> HMAC(token,"s"+sc+cc) ; both derive HMAC(token,"k"+sc+cc).
  Bytes first(4 + CHALLENGE);
  read_all(first.data(), first.size());
  Bytes am = magic("RTA");
  if (!std::equal(am.begin(), am.end(), first.begin()))
    throw std::runtime_error("server did not start wire authentication");
  Bytes sc(first.begin() + 4, first.end());

  Bytes cc(CHALLENGE);
  std::random_device rd;
  for (auto& b : cc) b = uint8_t(rd());

  auto proof_input = [&](char dir) {
    Bytes m{uint8_t(dir)};
    m.insert(m.end(), sc.begin(), sc.end());
    m.insert(m.end(), cc.begin(), cc.end());
    return m;
  };
  Bytes proof = hmac_sha256(token, proof_input('c'));
  Bytes out = cc;
  out.insert(out.end(), proof.begin(), proof.end());
  write_all(out.data(), out.size());

  Bytes server_proof(32);
  read_all(server_proof.data(), server_proof.size());
  if (!const_time_eq(server_proof, hmac_sha256(token, proof_input('s'))))
    throw std::runtime_error("server failed mutual authentication");
  mac_key_ = hmac_sha256(token, proof_input('k'));
}

static Bytes frame_tag(const Bytes& key, char dir, uint64_t seq,
                       const Bytes& body) {
  Blake2b b(MAC_SIZE, key);
  uint8_t d = uint8_t(dir);
  b.update(&d, 1);
  uint8_t seqb[8];
  for (int i = 0; i < 8; i++) seqb[i] = uint8_t(seq >> (8 * i));
  b.update(seqb, 8);
  b.update(body);
  return b.digest();
}

void Client::send_frame(const Bytes& body) {
  Bytes out = magic("RTX");
  uint32_t len = uint32_t(body.size());
  for (int i = 0; i < 4; i++) out.push_back(uint8_t(len >> (8 * i)));
  out.insert(out.end(), body.begin(), body.end());
  if (!mac_key_.empty()) {
    Bytes tag = frame_tag(mac_key_, 'C', send_seq_++, body);
    out.insert(out.end(), tag.begin(), tag.end());
  }
  write_all(out.data(), out.size());
}

Bytes Client::recv_frame() {
  Bytes hdr(8);
  read_all(hdr.data(), hdr.size());
  Bytes xm = magic("RTX");
  if (!std::equal(xm.begin(), xm.end(), hdr.begin()))
    throw std::runtime_error("unexpected reply magic");
  uint32_t len = 0;
  for (int i = 3; i >= 0; i--) len = (len << 8) | hdr[4 + i];
  Bytes body(len);
  read_all(body.data(), body.size());
  if (!mac_key_.empty()) {
    Bytes tag(MAC_SIZE);
    read_all(tag.data(), tag.size());
    if (!const_time_eq(tag, frame_tag(mac_key_, 'S', recv_seq_++, body)))
      throw std::runtime_error("reply MAC verification failed");
  }
  return body;
}

XValue Client::call(const std::string& method, XDict args) {
  Envelope req{KIND_REQUEST, true, ++next_msg_id_, method,
               XValue(std::move(args))};
  send_frame(req.encode());
  for (;;) {
    Envelope reply = Envelope::decode(recv_frame());
    if (reply.kind == KIND_PUSH) continue;  // sync client: skip pushes
    if (!reply.has_msg_id || reply.msg_id != req.msg_id) continue;
    if (reply.kind == KIND_ERROR)
      throw std::runtime_error("remote error in " + method + ": " +
                               reply.data.repr());
    if (reply.data.is_error_dict())
      throw std::runtime_error("error from " + method + ": " +
                               reply.data.at("error").repr());
    return reply.data;
  }
}

// ---------------------------------------------------------- proxy ops

XValue Client::hello() { return call("xhello", {}); }

Bytes Client::put(XValue value) {
  XDict args;
  args.emplace("value", std::move(value));
  return call("xput", std::move(args)).at("ref").as_bytes();
}

XValue Client::get(const Bytes& ref, double timeout_s) {
  XDict args;
  args.emplace("refs", XValue(XList{XValue(ref)}));
  args.emplace("timeout_s", XValue(timeout_s));
  XValue reply = call("xget", std::move(args));
  return reply.at("values").as_list().at(0);
}

Bytes Client::submit(const std::string& fn_name, XList args, XDict kwargs) {
  XDict d;
  d.emplace("name", XValue(fn_name));
  d.emplace("args", XValue(std::move(args)));
  d.emplace("kwargs", XValue(std::move(kwargs)));
  return call("xcall", std::move(d)).at("ref").as_bytes();
}

Bytes Client::actor_get(const std::string& name) {
  XDict d;
  d.emplace("name", XValue(name));
  return call("xactor_get", std::move(d)).at("actor_id").as_bytes();
}

Bytes Client::actor_call(const Bytes& actor_id, const std::string& method,
                         XList args, XDict kwargs) {
  XDict d;
  d.emplace("actor_id", XValue(actor_id));
  d.emplace("method", XValue(method));
  d.emplace("args", XValue(std::move(args)));
  d.emplace("kwargs", XValue(std::move(kwargs)));
  return call("xactor_call", std::move(d)).at("ref").as_bytes();
}

void Client::kv_put(const std::string& key, const Bytes& value) {
  XDict d;
  d.emplace("key", XValue(key));
  d.emplace("value", XValue(value));
  call("xkv_put", std::move(d));
}

std::optional<Bytes> Client::kv_get(const std::string& key) {
  XDict d;
  d.emplace("key", XValue(key));
  XValue reply = call("xkv_get", std::move(d));
  const XValue& v = reply.at("value");
  if (v.is_none()) return std::nullopt;
  return v.as_bytes();
}

void Client::release(const Bytes& ref) {
  XDict d;
  d.emplace("refs", XValue(XList{XValue(ref)}));
  call("xrelease", std::move(d));
}

XValue Client::ref_arg(const Bytes& ref) {
  XDict d;
  d.emplace("$ref", XValue(ref));
  return XValue(std::move(d));
}

}  // namespace raytpu
