// raytpu_cli — command-line driver for the C++ client (and its test
// harness: tests/test_xlang_cpp.py shells out to this binary).
//
// Usage:
//   raytpu_cli selftest
//       print crypto vectors (sha256/hmac/blake2b of fixed inputs) for
//       cross-checking against Python hashlib.
//   raytpu_cli --addr HOST:PORT [--token-hex HEX] CMD...
//     hello
//     call NAME [ARG...]          submit + get the result
//     submit NAME [ARG...]        submit, print ref hex (no wait)
//     get REFHEX
//     put ARG                     print ref hex
//     kvput KEY ARG               value must be b:HEX or s:text
//     kvget KEY
//     actorcall NAME METHOD [ARG...]   named actor, submit + get
//     exec CMD... [-- CMD...]...  several commands on ONE connection
//                                 (refs are session-scoped; @N names the
//                                 ref produced by the Nth sub-command)
//     worker [--max-tasks N] [--idle-exit] [--poll-timeout S]
//                                 HOST tasks: register the built-in C++
//                                 functions (cxx.add/mul/upper/sum/fail),
//                                 pull tasks, execute natively, reply.
//                                 Exits after N tasks / one idle poll.
//
// ARG syntax: i:123  f:1.5  s:text  b:hex  true  false  null
//             ref:REFHEX (object-ref argument; REFHEX may be @N in exec)

#include <cstdio>
#include <cstring>
#include <iostream>
#include <string>

#include "raytpu/client.hpp"
#include "raytpu/worker.hpp"

using namespace raytpu;

static XValue parse_arg(const std::string& a) {
  if (a == "true") return XValue(true);
  if (a == "false") return XValue(false);
  if (a == "null") return XValue();
  if (a.rfind("i:", 0) == 0) return XValue(int64_t(std::stoll(a.substr(2))));
  if (a.rfind("f:", 0) == 0) return XValue(std::stod(a.substr(2)));
  if (a.rfind("s:", 0) == 0) return XValue(a.substr(2));
  if (a.rfind("b:", 0) == 0) return XValue(from_hex(a.substr(2)));
  if (a.rfind("ref:", 0) == 0) return Client::ref_arg(from_hex(a.substr(4)));
  throw std::runtime_error("bad arg (want i:/f:/s:/b:/ref:/true/false/null): " + a);
}

static int selftest() {
  // Vectors printed for the Python side to compare against hashlib.
  Bytes abc{'a', 'b', 'c'};
  Bytes key{'k', 'e', 'y'};
  std::printf("sha256_abc=%s\n", to_hex(sha256(abc)).c_str());
  std::printf("sha256_empty=%s\n", to_hex(sha256({})).c_str());
  std::printf("hmac_key_abc=%s\n", to_hex(hmac_sha256(key, abc)).c_str());
  std::printf("blake2b16_abc=%s\n", to_hex(blake2b(abc, 16)).c_str());
  std::printf("blake2b16_key_abc=%s\n",
              to_hex(blake2b(abc, 16, key)).c_str());
  Bytes big(300);  // multi-block message
  for (size_t i = 0; i < big.size(); i++) big[i] = uint8_t(i);
  std::printf("blake2b16_key_big=%s\n",
              to_hex(blake2b(big, 16, key)).c_str());
  std::printf("sha256_big=%s\n", to_hex(sha256(big)).c_str());
  // XValue roundtrip sanity.
  XDict d;
  d.emplace("i", XValue(int64_t(-7)));
  d.emplace("l", XValue(XList{XValue("x"), XValue(1.5), XValue()}));
  Bytes enc;
  XValue(d).encode(enc);
  size_t pos = 0;
  XValue back = XValue::decode(enc, pos);
  std::printf("xvalue_roundtrip=%s\n",
              (pos == enc.size() && back.repr() == XValue(d).repr()) ? "ok"
                                                                     : "FAIL");
  std::printf("xvalue_hex=%s\n", to_hex(enc).c_str());
  return 0;
}

int main(int argc, char** argv) {
  try {
    std::string addr, token;
    int i = 1;
    for (; i < argc; i++) {
      std::string a = argv[i];
      if (a == "--addr" && i + 1 < argc)
        addr = argv[++i];
      else if (a == "--token-hex" && i + 1 < argc)
        token = argv[++i];
      else
        break;
    }
    if (i >= argc) {
      std::fprintf(stderr, "no command\n");
      return 2;
    }
    std::string cmd = argv[i++];
    if (cmd == "selftest") return selftest();

    auto colon = addr.rfind(':');
    if (colon == std::string::npos) {
      std::fprintf(stderr, "--addr HOST:PORT required\n");
      return 2;
    }
    Client client(addr.substr(0, colon),
                  uint16_t(std::stoi(addr.substr(colon + 1))), token);

    if (cmd == "exec") {
      // Sub-commands share this one connection/session. Each produced
      // ref is remembered; "@N" in ref:/get/ args resolves to the Nth.
      std::vector<Bytes> made_refs;
      auto resolve_hex = [&](const std::string& h) {
        if (!h.empty() && h[0] == '@')
          return made_refs.at(size_t(std::stoul(h.substr(1))));
        return from_hex(h);
      };
      while (i < argc) {
        std::string sub = argv[i++];
        XList args;
        auto take_args = [&]() {
          while (i < argc && std::strcmp(argv[i], "--") != 0) {
            std::string a = argv[i++];
            if (a.rfind("ref:", 0) == 0)
              args.push_back(Client::ref_arg(resolve_hex(a.substr(4))));
            else
              args.push_back(parse_arg(a));
          }
        };
        if (sub == "put") {
          take_args();
          made_refs.push_back(client.put(args.at(0)));
          std::printf("ref=@%zu\n", made_refs.size() - 1);
        } else if (sub == "submit") {
          std::string name = argv[i++];
          take_args();
          made_refs.push_back(client.submit(name, std::move(args)));
          std::printf("ref=@%zu\n", made_refs.size() - 1);
        } else if (sub == "call") {
          std::string name = argv[i++];
          take_args();
          Bytes ref = client.submit(name, std::move(args));
          made_refs.push_back(ref);
          std::printf("%s\n", client.get(ref).repr().c_str());
        } else if (sub == "get") {
          std::string h = argv[i++];
          std::printf("%s\n", client.get(resolve_hex(h)).repr().c_str());
          take_args();
        } else {
          std::fprintf(stderr, "unknown exec sub-command %s\n", sub.c_str());
          return 2;
        }
        if (i < argc && std::strcmp(argv[i], "--") == 0) i++;
      }
      return 0;
    }

    auto rest_args = [&]() {
      XList args;
      for (; i < argc; i++) args.push_back(parse_arg(argv[i]));
      return args;
    };

    if (cmd == "hello") {
      std::printf("%s\n", client.hello().repr().c_str());
    } else if (cmd == "call" || cmd == "submit") {
      std::string name = argv[i++];
      Bytes ref = client.submit(name, rest_args());
      if (cmd == "submit")
        std::printf("ref=%s\n", to_hex(ref).c_str());
      else
        std::printf("%s\n", client.get(ref).repr().c_str());
    } else if (cmd == "get") {
      Bytes ref = from_hex(argv[i++]);
      std::printf("%s\n", client.get(ref).repr().c_str());
    } else if (cmd == "put") {
      Bytes ref = client.put(parse_arg(argv[i++]));
      std::printf("ref=%s\n", to_hex(ref).c_str());
    } else if (cmd == "kvput") {
      std::string key = argv[i++];
      XValue v = parse_arg(argv[i++]);
      client.kv_put(key, v.tag() == XValue::Tag::Str
                             ? Bytes(v.as_str().begin(), v.as_str().end())
                             : v.as_bytes());
      std::printf("ok\n");
    } else if (cmd == "kvget") {
      auto v = client.kv_get(argv[i++]);
      if (v)
        std::printf("b:%s\n", to_hex(*v).c_str());
      else
        std::printf("null\n");
    } else if (cmd == "worker") {
      size_t max_tasks = 0;
      bool idle_exit = false;
      double poll_timeout = 10.0;
      for (; i < argc; i++) {
        std::string a = argv[i];
        if (a == "--max-tasks" && i + 1 < argc)
          max_tasks = size_t(std::stoul(argv[++i]));
        else if (a == "--idle-exit")
          idle_exit = true;
        else if (a == "--poll-timeout" && i + 1 < argc)
          poll_timeout = std::stod(argv[++i]);
        else
          throw std::runtime_error("unknown worker flag: " + a);
      }
      Worker worker(client, "cpp-worker");
      worker.register_fn("cxx.add", [](const XList& a) {
        return XValue(a.at(0).as_int() + a.at(1).as_int());
      });
      worker.register_fn("cxx.mul", [](const XList& a) {
        return XValue(a.at(0).as_float() * a.at(1).as_float());
      });
      worker.register_fn("cxx.upper", [](const XList& a) {
        std::string s = a.at(0).as_str();
        for (auto& c : s) c = char(std::toupper(uint8_t(c)));
        return XValue(s);
      });
      worker.register_fn("cxx.sum", [](const XList& a) {
        double total = 0;
        for (const auto& v : a.at(0).as_list()) total += v.as_float();
        return XValue(total);
      });
      worker.register_fn("cxx.fail", [](const XList&) -> XValue {
        throw std::runtime_error("deliberate failure from C++");
      });
      worker.register_with_cluster();
      std::printf("registered\n");
      std::fflush(stdout);
      size_t served = worker.serve(max_tasks, idle_exit, poll_timeout);
      // Graceful exit announces departure; queued tasks fail over rather
      // than hang the submitter.
      worker.unregister();
      std::printf("served=%zu\n", served);
    } else if (cmd == "actorcall") {
      std::string name = argv[i++];
      std::string method = argv[i++];
      Bytes aid = client.actor_get(name);
      Bytes ref = client.actor_call(aid, method, rest_args());
      std::printf("%s\n", client.get(ref).repr().c_str());
    } else {
      std::fprintf(stderr, "unknown command %s\n", cmd.c_str());
      return 2;
    }
    return 0;
  } catch (const std::exception& e) {
    std::fprintf(stderr, "error: %s\n", e.what());
    return 1;
  }
}
