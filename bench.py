"""Headline benchmark: flagship Llama training throughput on one TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "detail": {...}}

The reference publishes a scalability envelope, not tokens/sec (BASELINE.md);
the repo's north-star target is Llama-3-8B FSDP at >=45% MFU on v5e. On the
single available chip we run the same training math (fwd+bwd+adamw, bf16,
remat) at a ~1B-parameter configuration and report tokens/sec/chip with
model FLOPs utilization; vs_baseline = achieved_MFU / 0.45 target.

When the backend is TPU, `detail` additionally carries:
  * detail["kernels"] — Pallas kernels force-compiled under Mosaic
    (interpret=False) with numerics checks vs the jnp references and
    per-kernel us/op timings (flash_attention_fwd, ragged_paged_attention).
  * detail["serve"]   — paged-engine serving TTFT p50/p95 + decode tok/s.

TPU bring-up has failed three rounds running (probe timeouts; r3 also lost
the fallback number to an over-fat JSON line). This round: (a) the stdout
metric line is always slim — probe logs and stack dumps go to the
BENCH_probe.json sidecar, never the line; (b) 9 probe attempts cycle
default / unset-JAX_PLATFORMS / teardown-retry variants and are spread
across the full wall budget so a relay wedge that clears mid-run is still
caught; (c) each probe self-dumps stacks via faulthandler before its
timeout, and an exec wedge in the teardown variant discards the PJRT
client and retries on a fresh one.
"""

from __future__ import annotations

import json
import os
import sys
import time
import traceback

# The driver parses EXACTLY ONE stdout JSON line; r3's number was lost
# because the line embedded multi-KB probe stacks. The emitted line is now
# aggressively slimmed (no probe_log, no tracebacks, strings truncated) and
# the full fat diagnostics land in this sidecar instead.
_SIDECAR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_probe.json")

# Keys whose values are diagnostics, never metrics: stripped from the line.
_DIAG_KEYS = {"probe_log", "trace", "stdout", "stderr", "watchdog"}


def _slim(obj, max_str=200):
    """Deep-copy with diagnostic keys dropped and long strings truncated."""
    if isinstance(obj, dict):
        return {k: _slim(v, max_str) for k, v in obj.items()
                if k not in _DIAG_KEYS}
    if isinstance(obj, list):
        return [_slim(v, max_str) for v in obj]
    if isinstance(obj, str) and len(obj) > max_str:
        return obj[:max_str] + "..."
    return obj


def _actual_backend() -> str:
    """The backend jax actually resolved in THIS process — recorded in
    every emitted result so a BENCH_*.json can never claim TPU numbers
    that silently ran on CPU (or vice versa)."""
    try:
        import jax

        return str(jax.default_backend())
    except Exception:
        return "unknown"


def _emit(result: dict) -> None:
    """Write the fat result (+ probe log) to the sidecar, print a slim line.

    The slim line is guaranteed parseable and small: diagnostics are
    stripped, and as a last resort the detail dict is replaced wholesale
    rather than ever exceeding ~4 KB (r2's slim line parsed; r3's fat one
    did not — this path can no longer regress that way)."""
    result.setdefault("backend", _actual_backend())
    fat = dict(result)
    fat.setdefault("detail", {})
    fat["detail"] = dict(fat["detail"])
    fat["detail"]["probe_log"] = PROBE_LOG
    try:
        with open(_SIDECAR, "w") as f:
            json.dump(fat, f, indent=1, default=str)
    except OSError as exc:
        print(f"bench: sidecar write failed: {exc}", file=sys.stderr)
    slim = _slim(result)
    line = json.dumps(slim, default=str)
    if len(line) > 4000:
        slim["detail"] = {"truncated": True,
                          "backend": result.get("detail", {}).get("backend"),
                          "see": "BENCH_probe.json"}
        line = json.dumps(slim, default=str)
    print(line, flush=True)

PEAK_FLOPS = {
    "v5e": 197e12,   # bf16 peak per chip
    "v5p": 459e12,
    "v4": 275e12,
    "v6e": 918e12,
    "cpu": 1e11,     # nominal, keeps the metric finite off-TPU
}

# Filled in as legs complete; the watchdog emits it on a late hang so a
# stuck serve/kernel leg can't lose an already-measured training number.
PARTIAL_RESULT = None
PROBE_LOG = []

# TPU can surface as platform "tpu" (native libtpu) or "axon" (a PJRT
# plugin proxying a remote chip through a local relay; Pallas lowering
# rules are aliased so kernels compile under Mosaic either way). Single
# source of truth lives in ray_tpu.ops (imports no jax at module level,
# so probe-before-jax-import ordering is preserved).
from ray_tpu.ops import TPU_PLATFORMS

# The probe dumps all thread stacks to stderr just before the parent's
# timeout would kill it, so a hang inside PJRT_Client_Create / the relay
# claim leg is diagnosable from the bench artifact alone.
_PROBE_SCRIPT = """
import faulthandler, os, sys, time
faulthandler.dump_traceback_later({dump_after}, exit=True)
t0 = time.monotonic()
print('JAX_PLATFORMS_ENV=' + os.environ.get('JAX_PLATFORMS', '<unset>'),
      file=sys.stderr)
import jax
print('IMPORT_SECS=%.1f' % (time.monotonic() - t0), file=sys.stderr)
print('PLATFORM=' + jax.default_backend())
d = jax.devices()[0]
print('KIND=' + d.device_kind)
print('NDEV=%d' % jax.device_count())
print('INIT_SECS=%.1f' % (time.monotonic() - t0), file=sys.stderr)
# Device listing can succeed while EXECUTION is wedged (axon relay failure
# mode seen r3): a real compile+run must finish or the probe is a failure.
# The exec runs in a daemon thread so a wedged PJRT call can't pin the
# probe past its deadline; on a wedge the 'teardown' variant discards the
# client (jax.extend.backend.clear_backends) and retries on a fresh one —
# relay wedges observed in r3 are per-connection, not per-chip.
import threading


def _try_exec(timeout_s):
    out = {{}}
    def run():
        try:
            import jax.numpy as jnp
            x = jnp.ones((128, 128)) @ jnp.ones((128, 128))
            out['v'] = float(x[0, 0])
        except Exception as exc:
            out['err'] = repr(exc)
    th = threading.Thread(target=run, daemon=True)
    th.start()
    th.join(timeout_s)
    if 'err' in out:
        print('EXEC_ERR=' + out['err'][:200], file=sys.stderr)
    return out.get('v') == 128.0


ok = _try_exec({exec_timeout})
if not ok and {teardown}:
    print('EXEC_WEDGED=1 tearing down backend', file=sys.stderr)
    try:
        import jax.extend.backend
        jax.extend.backend.clear_backends()
    except Exception as exc:
        print('TEARDOWN_ERR=' + repr(exc)[:200], file=sys.stderr)
    ok = _try_exec({exec_timeout})
assert ok, 'matmul exec failed/wedged'
print('EXEC_OK=1')
print('EXEC_SECS=%.1f' % (time.monotonic() - t0), file=sys.stderr)
"""


def detect_peak() -> float:
    import os

    import jax

    if jax.default_backend() not in TPU_PLATFORMS:
        return PEAK_FLOPS["cpu"]
    kind = jax.devices()[0].device_kind.lower().replace(" ", "")
    for name, peak in PEAK_FLOPS.items():
        if name != "cpu" and name in kind:
            return peak
    # Proxied chips can report an opaque device kind; the relay exports
    # the generation out-of-band.
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()
    return PEAK_FLOPS.get(gen, PEAK_FLOPS["v5e"])


def init_backend(probes: int = 9,
                 total_budget_s: float = 1650.0) -> str:
    """Bring up the jax backend robustly.

    Failure modes seen in rounds 1-3: the TPU plugin raised once (unhandled),
    hung indefinitely during init (the axon relay's claim leg can block
    forever), and — r3 — listed devices fine but WEDGED on execution for
    >290 s. Neither raise nor hang is recoverable in-process, so each probe
    runs in a SUBPROCESS with a timeout and a faulthandler stack dump just
    before that timeout. Probes cycle three variants:
      default  — environment as-is (JAX_PLATFORMS=axon on relay hosts)
      unset    — JAX_PLATFORMS unset (plain plugin discovery)
      teardown — exec wedge triggers jax.extend.backend.clear_backends()
                 and a retry on a fresh client inside the same subprocess
    and are SPREAD across the full wall budget (relay wedges clear in
    minutes — r3 burned its whole budget in the first 25 min and never
    re-looked): leftover slack is distributed as growing sleeps between
    attempts. Outcomes land in PROBE_LOG → BENCH_probe.json sidecar (never
    in the stdout metric line). On persistent failure we force the CPU
    platform before importing jax here, so the benchmark always produces a
    parseable JSON line.

    Returns the platform the parent should use ("tpu"/"axon" or "cpu")."""
    import subprocess

    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        # The env var alone doesn't stick: a sitecustomize may already have
        # registered a TPU plugin and rewritten jax_platforms in-process.
        from __graft_entry__ import _force_cpu_platform

        _force_cpu_platform(1)
        PROBE_LOG.append({"skipped": "JAX_PLATFORMS=cpu pinned by caller"})
        return "cpu"
    # Front-loaded timeouts (healthy bring-up is <60 s; wedges burn the
    # slot), trailing probes shorter so all 9 fit the budget even if every
    # one hangs: sum = 1560 s < 1650.
    timeouts = [240, 240, 240, 180, 180, 180, 120, 120, 60][:probes]
    variants = ["default", "unset", "teardown"]
    t_start = time.monotonic()
    deadline = t_start + total_budget_s
    clean_non_tpu_envs: set = set()
    for attempt in range(probes):
        variant = variants[attempt % len(variants)]
        probe_timeout_s = timeouts[attempt]
        # Two exec attempts (wedge + teardown retry) plus a jax-import
        # margin must fit under the faulthandler deadline, or the retry the
        # teardown variant exists for gets killed mid-run.
        dump_after = max(30, int(probe_timeout_s) - 10)
        script = _PROBE_SCRIPT.format(
            dump_after=dump_after,
            exec_timeout=max(15, (dump_after - 30) // 2),
            teardown=repr(variant == "teardown"))
        env = dict(os.environ)
        if variant == "unset":
            env.pop("JAX_PLATFORMS", None)
        entry = {"attempt": attempt + 1, "variant": variant,
                 "timeout_s": probe_timeout_s,
                 "at_s": round(time.monotonic() - t_start, 1),
                 "jax_platforms": env.get("JAX_PLATFORMS", "<unset>")}
        t0 = time.monotonic()
        try:
            r = subprocess.run(
                [sys.executable, "-c", script],
                capture_output=True, text=True, timeout=probe_timeout_s,
                env=env)
            entry.update(rc=r.returncode,
                         secs=round(time.monotonic() - t0, 1),
                         stdout=r.stdout[-500:], stderr=r.stderr[-3000:])
            platform = None
            exec_ok = False
            for line in r.stdout.splitlines():
                if line.startswith("PLATFORM="):
                    platform = line.split("=", 1)[1]
                elif line.startswith("EXEC_OK="):
                    exec_ok = True
            entry["exec_ok"] = exec_ok
            PROBE_LOG.append(entry)
            if (platform in TPU_PLATFORMS and r.returncode == 0
                    and exec_ok):
                if variant == "unset":
                    os.environ.pop("JAX_PLATFORMS", None)
                return platform
            if (r.returncode == 0 and platform is not None
                    and platform not in TPU_PLATFORMS):
                # A clean non-TPU report is definitive FOR THIS ENV SHAPE
                # (teardown reuses the default env, so two shapes exist).
                # Once both shapes answered cleanly there is no TPU to
                # wait for — stop without burning the spread budget.
                clean_non_tpu_envs.add(entry["jax_platforms"])
                if len(clean_non_tpu_envs) >= (
                        2 if "JAX_PLATFORMS" in os.environ else 1):
                    entry["definitive"] = True
                    break
                continue  # clean answer: try the other shape immediately
        except subprocess.TimeoutExpired as exc:
            def _tail(v):
                if isinstance(v, bytes):
                    return v[-3000:].decode("utf-8", "replace")
                return (v or "")[-3000:]
            entry.update(timeout=True,
                         secs=round(time.monotonic() - t0, 1),
                         stdout=_tail(exc.stdout), stderr=_tail(exc.stderr))
            PROBE_LOG.append(entry)
        remaining = probes - 1 - attempt
        if remaining <= 0:
            break
        # Spread the remaining slack over the remaining inter-attempt gaps
        # so the LAST probe still fires near the end of the budget: a
        # wedge that clears at minute 20 is caught by a late probe instead
        # of every probe having burned out in the first 10 minutes.
        slack = (deadline - time.monotonic()) - sum(timeouts[attempt + 1:])
        sleep_s = max(5.0, slack / remaining)
        if time.monotonic() + sleep_s + timeouts[attempt + 1] > deadline:
            PROBE_LOG.append({"stopped": "probe budget exhausted",
                              "budget_s": total_budget_s})
            break
        time.sleep(sleep_s)
    print("bench: TPU backend unavailable; falling back to CPU",
          file=sys.stderr)
    # Env vars alone are NOT enough: the host's sitecustomize may have
    # already imported jax with the TPU plugin registered, in which case
    # main()'s first jax call would still initialize (and hang on) the
    # broken backend. Force the in-process config too.
    from __graft_entry__ import _force_cpu_platform

    _force_cpu_platform(1)
    return "cpu"


def _emit_error_json(msg: str) -> None:
    _emit({
        "metric": "llama1b_train_tokens_per_sec_per_chip",
        "value": 0,
        "unit": "tokens/s",
        "vs_baseline": 0,
        "detail": {"error": msg[:300]},
    })


def _sync(x) -> float:
    """Force completion via a device->host scalar fetch: block_until_ready
    can be a no-op on remote-execution PJRT backends."""
    import jax.numpy as jnp

    return float(jnp.asarray(x).reshape(-1)[0])


def kernels_bench(on_tpu: bool) -> dict:
    """Force-compile the Pallas kernels (interpret=False on TPU — the Mosaic
    compiler, not interpret mode), check numerics vs the jnp references, and
    time them. Returns a dict for detail["kernels"]."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from ray_tpu.ops import attention as att
    from ray_tpu.ops import paged_attention as pa

    interpret = not on_tpu
    out: dict = {"interpret": interpret}
    n_iters = 20 if on_tpu else 2  # interpret mode is minutes-per-op slow

    def timeit(fn, *args, iters=None):
        iters = n_iters if iters is None else iters
        fn(*args)  # compile
        _sync(fn(*args))
        t0 = time.perf_counter()
        for _ in range(iters):
            r = fn(*args)
        _sync(r)
        return (time.perf_counter() - t0) / iters * 1e6  # us/op

    # --- flash attention forward -----------------------------------------
    try:
        b, sq, h, d = (4, 2048, 16, 128) if on_tpu else (1, 128, 2, 64)
        key = jax.random.key(0)
        q = jax.random.normal(key, (b, sq, h, d), dtype=jnp.bfloat16)
        k = jax.random.normal(key, (b, sq, h // 2, d), dtype=jnp.bfloat16)
        v = jax.random.normal(key, (b, sq, h // 2, d), dtype=jnp.bfloat16)
        flash = jax.jit(lambda q, k, v: att.flash_attention_fwd(
            q, k, v, causal=True, interpret=interpret))
        ref = jax.jit(lambda q, k, v: att.mha_reference(q, k, v, causal=True))
        got, want = np.asarray(flash(q, k, v)), np.asarray(ref(q, k, v))
        err = float(np.max(np.abs(got.astype(np.float32)
                                  - want.astype(np.float32))))
        us = timeit(flash, q, k, v)
        # attention flops: 4 * b*h*sq^2*d (qk + pv, fwd), causal halves it
        flops = 4 * b * h * sq * sq * d / 2
        out["flash_attention_fwd"] = {
            "ok": err < 0.06, "max_err": round(err, 5),
            "us_per_op": round(us, 1),
            "tflops": round(flops / (us * 1e-6) / 1e12, 2),
            "shape": [b, sq, h, d],
        }
    except Exception as exc:
        out["flash_attention_fwd"] = {
            "ok": False, "error": f"{type(exc).__name__}: {exc}",
            "trace": traceback.format_exc()[-1500:]}

    # --- flash attention backward (training path) ------------------------
    try:
        b, sq, h, d = (4, 2048, 16, 128) if on_tpu else (1, 128, 2, 64)
        key = jax.random.key(3)
        q = jax.random.normal(key, (b, sq, h, d), dtype=jnp.bfloat16)
        k = jax.random.normal(key, (b, sq, h // 2, d), dtype=jnp.bfloat16)
        v = jax.random.normal(key, (b, sq, h // 2, d), dtype=jnp.bfloat16)
        grad_flash = jax.jit(jax.grad(lambda q, k, v: (att.flash_attention(
            q, k, v, causal=True, interpret=interpret) ** 2).sum(),
            argnums=(0, 1, 2)))
        grad_ref = jax.jit(jax.grad(lambda q, k, v: (att.mha_reference(
            q, k, v, causal=True) ** 2).sum(), argnums=(0, 1, 2)))
        gf = grad_flash(q, k, v)
        gr = grad_ref(q, k, v)
        err = max(float(np.max(np.abs(np.asarray(a, dtype=np.float32)
                                      - np.asarray(b_, dtype=np.float32))))
                  for a, b_ in zip(gf, gr))
        us = timeit(lambda *a: grad_flash(*a)[0], q, k, v)
        # bwd attention flops ~ 2.5x fwd (dq + dkv recompute), causal halves
        flops = 10 * b * h * sq * sq * d / 2
        out["flash_attention_bwd"] = {
            "ok": err < 0.75,  # grad-of-square amplifies bf16 noise
            "max_err": round(err, 4),
            "us_per_op": round(us, 1),
            "tflops": round(flops / (us * 1e-6) / 1e12, 2),
            "shape": [b, sq, h, d],
        }
    except Exception as exc:
        out["flash_attention_bwd"] = {
            "ok": False, "error": f"{type(exc).__name__}: {exc}",
            "trace": traceback.format_exc()[-1500:]}

    # --- ragged paged attention (decode shape) ---------------------------
    try:
        if on_tpu:
            S, Bq, H, hd, K, P, ps, mp = 16, 1, 16, 128, 8, 2048, 16, 128
        else:
            S, Bq, H, hd, K, P, ps, mp = 2, 1, 2, 64, 1, 16, 16, 4
        key = jax.random.key(1)
        q = jax.random.normal(key, (S, Bq, H, hd), dtype=jnp.bfloat16)
        kp = jax.random.normal(key, (K, P, ps, hd), dtype=jnp.bfloat16)
        vp = jax.random.normal(key, (K, P, ps, hd), dtype=jnp.bfloat16)
        rng = np.random.RandomState(0)
        kv_lens = jnp.asarray(rng.randint(ps, mp * ps, S), dtype=jnp.int32)
        bt = jnp.asarray(rng.randint(0, P, (S, mp)), dtype=jnp.int32)
        q_pos = kv_lens - Bq
        paged = jax.jit(lambda *a: pa.ragged_paged_attention(
            *a, interpret=interpret))
        pref = jax.jit(pa.ragged_paged_attention_reference)
        got = np.asarray(paged(q, kp, vp, bt, kv_lens, q_pos))
        want = np.asarray(pref(q, kp, vp, bt, kv_lens, q_pos))
        err = float(np.max(np.abs(got.astype(np.float32)
                                  - want.astype(np.float32))))
        us = timeit(paged, q, kp, vp, bt, kv_lens, q_pos)
        out["ragged_paged_attention"] = {
            "ok": err < 0.06, "max_err": round(err, 5),
            "us_per_op": round(us, 1),
            "shape": {"S": S, "H": H, "hd": hd, "page": ps,
                      "mean_ctx": int(np.mean(np.asarray(kv_lens)))},
        }
    except Exception as exc:
        out["ragged_paged_attention"] = {
            "ok": False, "error": f"{type(exc).__name__}: {exc}",
            "trace": traceback.format_exc()[-1500:]}
    return out


def serve_bench_result(backend: str) -> dict:
    """Serving TTFT + decode throughput on one chip via the native paged
    engine (north star: 8B <150ms p50 TTFT on v5e; scaled-down model on the
    single dev chip). Returns a dict for detail["serve"]."""
    import numpy as np

    import jax

    from ray_tpu.llm.engine import LLMEngine
    from ray_tpu.llm.model_runner import ModelRunner
    from ray_tpu.llm.sampling import SamplingParams
    from ray_tpu.models import llama

    on_tpu = backend != "cpu"
    if on_tpu:
        # ~1.9B-param llama (hd=128 so the Pallas kernel engages) in bf16.
        config = llama.LlamaConfig(
            vocab_size=32000, d_model=2048, n_layers=18, n_heads=16,
            n_kv_heads=8, d_ff=8192, max_seq=2048)
        num_blocks, prompt_len, gen_tokens, n_requests = 1024, 512, 64, 8
    else:
        config = llama.LlamaConfig.tiny(max_seq=128)
        num_blocks, prompt_len, gen_tokens, n_requests = 64, 48, 8, 3

    params = llama.init_params(config, jax.random.key(0))
    runner = ModelRunner(config, params, num_blocks=num_blocks,
                         block_size=16, chunk_size=512 if on_tpu else 16)
    # ONE engine serves every leg: decode_multi_step=8 makes warmup
    # compile the k-step scan programs alongside the whole grid, and the
    # per-dispatch multi_step flag flips between measurement modes — no
    # second engine, no duplicate warmup (the full run must fit the
    # watchdog budget).
    engine = LLMEngine(runner, max_batch_size=8,
                       prefill_chunk=512 if on_tpu else 16,
                       pipeline_depth=8, decode_multi_step=8)
    rng = np.random.RandomState(0)
    prompt = rng.randint(1, config.vocab_size, prompt_len).tolist()

    # Warmup: precompile the full bucket grid (vLLM-TPU-style), then one
    # real request for the host-side paths. Without the grid warmup the
    # prefix-cache leg's short-suffix bucket compiled INSIDE the timed
    # region (13.2 s "TTFT" in the first r4 live run).
    engine.warmup()  # compiles the k-step scan programs too (flag is 8)
    engine.generate([prompt], SamplingParams(max_tokens=4))
    engine.multi_step = 1  # sequential-latency legs run single-step

    ttfts, decode_times, decoded = [], [], 0
    for _ in range(n_requests):
        p = rng.randint(1, config.vocab_size, prompt_len).tolist()
        t0 = time.perf_counter()
        first_at = None
        for i, _tok in enumerate(engine.stream(
                p, SamplingParams(max_tokens=gen_tokens))):
            if i == 0:
                first_at = time.perf_counter() - t0
        total = time.perf_counter() - t0
        ttfts.append(first_at)
        decode_times.append(total - first_at)
        decoded += gen_tokens - 1
    # Prefix-cache TTFT: a request whose prompt shares a long cached
    # prefix (the agent/system-prompt serving pattern) skips that
    # prefill compute entirely.
    shared = rng.randint(1, config.vocab_size, prompt_len).tolist()
    t0 = time.perf_counter()
    for i, _tok in enumerate(engine.stream(
            shared, SamplingParams(max_tokens=4))):
        if i == 0:
            cold_ttft = time.perf_counter() - t0
    tail = rng.randint(1, config.vocab_size, 8).tolist()
    t0 = time.perf_counter()
    for i, _tok in enumerate(engine.stream(
            shared[:-8] + tail, SamplingParams(max_tokens=4))):
        if i == 0:
            warm_ttft = time.perf_counter() - t0
    ttfts.sort()
    p50 = ttfts[len(ttfts) // 2]
    p95 = ttfts[min(len(ttfts) - 1, int(len(ttfts) * 0.95))]
    decode_tok_s = decoded / max(sum(decode_times), 1e-9)

    # Multi-step decode probe: k tokens per dispatch via the on-device
    # scan. Same engine, flag flipped — the scan programs were compiled
    # in the single warmup above. On dispatch-latency-bound setups (this
    # chip arrives over a relay) this is the decode-throughput lever;
    # the headline decode number reports the better of the two.
    multi_k = 8
    multi_tok_s = None
    try:
        engine.multi_step = multi_k
        m_decoded, m_time = 0, 0.0
        for _ in range(n_requests):
            p = rng.randint(1, config.vocab_size, prompt_len).tolist()
            t0 = time.perf_counter()
            first_at = None
            for i, _tok in enumerate(engine.stream(
                    p, SamplingParams(max_tokens=gen_tokens))):
                if i == 0:
                    first_at = time.perf_counter() - t0
            m_time += time.perf_counter() - t0 - first_at
            # The first yield lands after a FULL k-token dispatch, so the
            # post-first_at window covers gen_tokens - k tokens (counting
            # gen-1 like the single-step leg would inflate this number by
            # ~k/gen and could crown multi-step on measurement bias).
            m_decoded += max(gen_tokens - multi_k, 1)
        multi_tok_s = m_decoded / max(m_time, 1e-9)
    except Exception as exc:  # best-effort probe; never sinks the leg
        PROBE_LOG.append({"multi_step_decode": f"{type(exc).__name__}: "
                                               f"{str(exc)[:160]}"})

    # Throughput under load: all requests in flight at once — continuous
    # batching aggregates decode across the whole batch (the number that
    # scales serving cost, vs the latency-oriented sequential runs above).
    # Swept over concurrency levels for a SATURATION curve: past the
    # running-batch/page capacity, extra requests queue and the aggregate
    # should plateau, not fall — that plateau is the chip's serving
    # capacity. Each level is guarded; the first (base) level feeds the
    # headline throughput number.
    throughput_tok_s = None
    saturation = {}
    levels = ((n_requests, 32, 128) if on_tpu else (n_requests,))
    engine.multi_step = (multi_k if multi_tok_s
                         and multi_tok_s > decode_tok_s else 1)
    for level in levels:
        try:
            # Concurrent admission batches the prefills into ONE
            # (batch, chunk) dispatch — a bucket the LIGHT warmup above
            # deliberately skips (production servers warmup(full=True);
            # the full grid would blow the relay's watchdog budget here).
            # One untimed pass with the same admission shape compiles it;
            # fresh random prompts in the timed pass keep the prefix
            # cache cold so only programs are warm, not KV.
            warm_prompts = [rng.randint(1, config.vocab_size,
                                        prompt_len).tolist()
                            for _ in range(level)]
            engine.generate(warm_prompts, SamplingParams(max_tokens=8))
            prompts = [rng.randint(1, config.vocab_size,
                                   prompt_len).tolist()
                       for _ in range(level)]
            t0 = time.perf_counter()
            outs = engine.generate(prompts,
                                   SamplingParams(max_tokens=gen_tokens))
            wall = time.perf_counter() - t0
            total = sum(len(o.output_token_ids) for o in outs)
            level_tok_s = total / max(wall, 1e-9)
            saturation[level] = round(level_tok_s, 1)
            if level == n_requests:
                # Only the base level may feed the headline number —
                # promoting a higher-concurrency aggregate would compare
                # across rounds at different concurrency unmarked.
                throughput_tok_s = level_tok_s
        except Exception as exc:
            saturation[level] = (f"failed: {type(exc).__name__}: "
                                 f"{str(exc)[:120]}")
        PROBE_LOG.append({"serve_saturation": dict(saturation)})
    return {
        "ttft_p50_ms": round(p50 * 1000, 2),
        "ttft_p95_ms": round(p95 * 1000, 2),
        "prefix_cache": {
            "cold_ttft_ms": round(cold_ttft * 1000, 2),
            "cached_prefix_ttft_ms": round(warm_ttft * 1000, 2),
            "tokens_saved": int(
                engine.block_manager.prefix_tokens_saved),
        },
        "vs_target": round(0.150 / max(p50, 1e-9), 3),  # >1 beats 150ms
        "decode_tokens_per_sec": round(max(decode_tok_s, multi_tok_s or 0),
                                       1),
        "decode_single_step": round(decode_tok_s, 1),
        "decode_multi_step_k": multi_k,
        "decode_multi_step": (round(multi_tok_s, 1)
                              if multi_tok_s is not None else None),
        "throughput_tokens_per_sec": (round(throughput_tok_s, 1)
                                      if throughput_tok_s is not None
                                      else None),
        "saturation_curve": saturation,   # concurrency -> aggregate tok/s
        "prompt_len": prompt_len,
        "gen_tokens": gen_tokens,
        "requests": n_requests,
        "attention_impl": runner.attention_impl,
        "params_b": round(config.num_params() / 1e9, 3),
        "backend": jax.default_backend(),
    }


def serve_bench():
    """`python bench.py --serve`: standalone serving probe."""
    backend = init_backend()
    result = serve_bench_result(backend)
    _emit({
        "metric": "llm_serve_ttft_p50_ms",
        "value": result["ttft_p50_ms"],
        "unit": "ms",
        "vs_baseline": result["vs_target"],
        "detail": result,
    })


def kernels_main():
    """`python bench.py --kernels`: standalone Mosaic kernel validation."""
    backend = init_backend()
    result = kernels_bench(backend != "cpu")
    ok = all(v.get("ok") for v in result.values() if isinstance(v, dict))
    _emit({
        "metric": "pallas_kernels_ok",
        "value": int(ok),
        "unit": "bool",
        "vs_baseline": int(ok),
        "detail": {**result, "backend": backend},
    })


def main():
    global PARTIAL_RESULT
    backend = init_backend()
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models import llama

    on_tpu = backend != "cpu"
    if on_tpu:
        # ~0.9B params: fits one 16GB v5e chip with bf16 params + adam
        # moments (mu bf16, nu fp32). remat_policy="dots" (save matmul
        # outputs, recompute elementwise/scores) beat full remat 45.1% vs
        # 42.9% MFU in an interactive r3 sweep (driver-unverified); batch
        # 6/8 exceed HBM under this policy.
        config = llama.LlamaConfig(
            vocab_size=32000, d_model=2048, n_layers=14, n_heads=16,
            n_kv_heads=8, d_ff=7168, max_seq=2048, remat_policy="dots")
        batch, seq, steps = 4, 2048, 10
    else:  # smoke path for dev machines
        config = llama.LlamaConfig.tiny(max_seq=128)
        batch, seq, steps = 4, 128, 3

    opt = optax.adamw(1e-4, b1=0.9, b2=0.95,
                      mu_dtype=jnp.bfloat16)

    def init_state_for(cfg):
        @jax.jit
        def _init(key):
            params = llama.init_params(cfg, key)
            return {"params": params, "opt": opt.init(params)}

        return _init

    init_state = init_state_for(config)

    from functools import partial

    def make_step(cfg):
        """One jitted train step closed over cfg — shared by the impl
        probes and the headline run so probe math can never drift from
        the timed math, and the winner's compiled step is REUSED."""

        @partial(jax.jit, donate_argnums=(0,))
        def step(state, tokens):
            def loss(p):
                l, _m = llama.loss_fn(p, {"tokens": tokens}, cfg)
                return l

            l, grads = jax.value_and_grad(loss)(state["params"])
            updates, opt_state = opt.update(grads, state["opt"],
                                            state["params"])
            return {"params": optax.apply_updates(state["params"], updates),
                    "opt": opt_state}, l

        return step

    def longctx_probe(base_cfg, make_step, init_state_for,
                      deadline: Optional[float] = None):
        """Train-step throughput at long sequence, batch 1. Flash-only:
        past 4k the unfused reference attention materializes (1, h, s, s)
        fp32 scores — the Pallas fwd+bwd (ops/attention.py) is what makes
        long context fit at all. Remat policy per seq: "dots" up to 8k;
        "flash" (save the kernel's out+lse, skip its O(s^2) recompute in
        backward — models/llama.py) at 16k/32k, where "dots" busts HBM
        and full remat paid the quadratic kernel twice (42.9% MFU at 32k
        in r4). 3 timed steps after compile per point; each point is
        independently guarded so one OOM doesn't kill the leg."""
        import dataclasses as _dc

        points = []
        for lc_seq, policy in ((8192, "dots"), (16384, "flash"),
                               (32768, "flash")):
            if deadline is not None and time.monotonic() > deadline:
                points.append({"seq": lc_seq, "skipped": "budget"})
                continue
            try:
                cfg = _dc.replace(base_cfg, max_seq=lc_seq,
                                  attention_impl="flash",
                                  remat_policy=policy)
                lc_step = make_step(cfg)
                lc_state = init_state_for(cfg)(jax.random.key(2))
                lc_tokens = jax.random.randint(
                    jax.random.key(3), (1, lc_seq + 1), 0, cfg.vocab_size)
                lc_state, l = lc_step(lc_state, lc_tokens)  # compile+warm
                _ = float(l)
                t0 = time.perf_counter()
                n_steps = 3
                for _i in range(n_steps):
                    lc_state, l = lc_step(lc_state, lc_tokens)
                lc_loss = float(l)
                dt = time.perf_counter() - t0
                tok_s = lc_seq * n_steps / dt
                mfu = tok_s * cfg.flops_per_token(lc_seq) / detect_peak()
                del lc_state, lc_step
                points.append({"seq": lc_seq, "batch": 1,
                               "tokens_per_sec": round(tok_s, 1),
                               "mfu": round(mfu, 4), "steps": n_steps,
                               "loss": lc_loss, "remat_policy": policy,
                               "attention_impl": "flash"})
            except Exception as exc:
                points.append({"seq": lc_seq, "remat_policy": policy,
                               "error": f"{exc!r}"[:300]})
                # Drop the failed point's state/step NOW: leaving them
                # bound through gc.collect() would carry the OOM'd
                # buffers into the next (larger) point.
                lc_state = lc_step = None  # noqa: F841
            import gc as _gc

            _gc.collect()
            # Per-point sidecar flush: a watchdog kill mid-leg still
            # leaves every completed point in BENCH_probe.json.
            PROBE_LOG.append({"long_context": points[-1]})
            try:
                with open(_SIDECAR + ".partial", "w") as f:
                    json.dump({"probe_log": PROBE_LOG}, f, default=str)
            except OSError:
                pass
        return points

    # Attention impl self-selection: "auto" routes this config (hd=128,
    # seq=2048) through the Pallas flash fwd+bwd on TPU; the XLA-fused
    # reference won r3 at 45.1% MFU. Race short probes of both and train
    # with the winner — a regression in either path can't sink the
    # headline number. Probe outcomes land in PROBE_LOG so even a
    # watchdog-truncated run leaves the diagnostics in the sidecar.
    attn_probe = {}
    train_step = None
    if on_tpu:
        import dataclasses as _dc

        tokens0 = jax.random.randint(jax.random.key(1), (batch, seq + 1),
                                     0, config.vocab_size)
        candidates = {}
        for impl in ("reference", "flash"):
            step_fn = make_step(_dc.replace(config, attention_impl=impl))
            try:
                st = init_state(jax.random.key(0))
                for _i in range(2):   # compile + settle (matches main run)
                    st, l = step_fn(st, tokens0)
                    _ = float(l)
                t0 = time.perf_counter()
                for _i in range(5):
                    st, l = step_fn(st, tokens0)
                _ = float(l)
                attn_probe[impl] = round((time.perf_counter() - t0) / 5, 4)
                candidates[impl] = step_fn
            except Exception as exc:
                attn_probe[impl] = (f"failed: {type(exc).__name__}: "
                                    f"{str(exc)[:120]}")
            finally:
                # Free the probe's ~7GB of params+opt state even on the
                # failure path — r4's first live run OOM'd because a failed
                # probe's state survived into the headline run's init.
                st = l = None
            PROBE_LOG.append({"attn_probe": dict(attn_probe)})
        timed = {k: v for k, v in attn_probe.items() if isinstance(v, float)}
        attn_impl = min(timed, key=timed.get) if timed else "reference"
        config = _dc.replace(config, attention_impl=attn_impl)
        train_step = candidates.get(attn_impl)

        # Batch-size probe: flash attention's O(seq) activation memory can
        # fit batch 6/8 where the O(s^2) reference OOM'd in r3. Compare
        # tokens/s (not s/step) against the batch-4 winner and train with
        # whichever batch feeds the MXU best; OOM probes clean up after
        # themselves and simply lose the race.
        if train_step is not None and attn_impl in timed:
            batch_probe = {batch: round(batch * seq / timed[attn_impl], 1)}
            batch_policy = {batch: config.remat_policy}
            best_bsz, best_tok_s = batch, batch_probe[batch]
            best_step, best_policy = train_step, config.remat_policy
            # The batch-scaling curve (4/8/16 x 2048). "dots" exceeded HBM
            # at batch>=6 in r3, so the larger batches run the "flash"
            # remat policy (save the kernel's out+lse only; O(s) memory),
            # falling back to full remat. Each point is a fresh compile:
            # the r4 relay 500 at batch 8 came through remote_compile, so
            # a failed policy is recorded and the next one still tries.
            probe_t0 = time.monotonic()
            for bsz in (8, 16):
                # The curve runs BEFORE the headline: cap its budget so a
                # slow relay can't starve the headline out of the
                # watchdog window (each point is a fresh ~1-3 min
                # compile; the headline number must always land).
                if time.monotonic() - probe_t0 > 900:
                    batch_probe[bsz] = "skipped: probe budget"
                    continue
                for policy in ("flash", "full"):
                    st = l = None
                    try:
                        step_b = make_step(
                            _dc.replace(config, remat_policy=policy))
                        toks_b = jax.random.randint(
                            jax.random.key(1), (bsz, seq + 1), 0,
                            config.vocab_size)
                        st = init_state(jax.random.key(0))
                        for _i in range(2):   # compile + settle
                            st, l = step_b(st, toks_b)
                            _ = float(l)
                        t0 = time.perf_counter()
                        for _i in range(5):
                            st, l = step_b(st, toks_b)
                        _ = float(l)
                        sps = (time.perf_counter() - t0) / 5
                        tok_s_b = bsz * seq / sps
                        batch_probe[bsz] = round(tok_s_b, 1)
                        batch_policy[bsz] = policy
                        if tok_s_b > best_tok_s:
                            best_bsz, best_tok_s = bsz, tok_s_b
                            best_step, best_policy = step_b, policy
                        break
                    except Exception as exc:
                        batch_probe[f"{bsz}/{policy}"] = (
                            f"failed: {type(exc).__name__}: "
                            f"{str(exc)[:80]}")
                    finally:
                        st = l = None
                PROBE_LOG.append({"batch_probe": dict(batch_probe)})
            batch = best_bsz
            # The headline must run the policy its winning batch was
            # probed with — re-running a flash-policy winner under "dots"
            # would OOM at batch 8/16.
            train_step = best_step
            config = _dc.replace(config, remat_policy=best_policy)
            attn_probe["batch_tokens_per_s"] = batch_probe
            attn_probe["batch_remat_policy"] = batch_policy
    if train_step is None:
        train_step = make_step(config)

    state = init_state(jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (batch, seq + 1), 0,
                                config.vocab_size)

    # Warmup / compile (timing boundaries force device->host fetches).
    state, l = train_step(state, tokens)
    _ = float(l)
    state, l = train_step(state, tokens)
    _ = float(l)

    t0 = time.perf_counter()
    for _ in range(steps):
        state, l = train_step(state, tokens)
    final_loss = float(l)  # forces completion of the whole chain
    dt = time.perf_counter() - t0

    tokens_per_step = batch * seq
    tok_s = tokens_per_step * steps / dt
    flops_per_token = config.flops_per_token(seq)
    mfu = tok_s * flops_per_token / detect_peak()

    result = {
        "metric": "llama1b_train_tokens_per_sec_per_chip",
        "value": round(tok_s, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.45, 4),
        "detail": {
            "mfu": round(mfu, 4),
            "params_b": round(config.num_params() / 1e9, 3),
            "batch_tokens": tokens_per_step,
            "steps": steps,
            "backend": jax.default_backend(),
            "device_kind": jax.devices()[0].device_kind,
            "loss": final_loss,
            "attention_impl": config.attention_impl,
            "attn_probe_s_per_step": attn_probe,
        },
    }
    PARTIAL_RESULT = result
    # Free the training state BEFORE the secondary legs: the serve leg
    # allocates a ~1.9B-param model + KV cache and must not compete with
    # ~7GB of dead training state on a 16GB chip.
    del state

    # Secondary legs ride the same invocation when we reached the chip —
    # each is best-effort: a failure is recorded, not fatal, and the
    # watchdog emits PARTIAL_RESULT if one hangs.
    if on_tpu:
        try:
            result["detail"]["kernels"] = kernels_bench(True)
        except Exception as exc:
            result["detail"]["kernels"] = {"error": f"{exc!r}"}
        PARTIAL_RESULT = result
        # Long-context leg BEFORE serve: it allocates 0.9B params + opt +
        # 8k-token activations, which don't fit alongside the serve
        # engine's 1.3B model + KV pool (the leg ran last in an earlier
        # revision and died RESOURCE_EXHAUSTED); its own state is freed
        # before serve allocates. Failure is recorded, not fatal.
        try:
            result["detail"]["long_context"] = longctx_probe(
                config, make_step, init_state_for)
        except Exception as exc:
            result["detail"]["long_context"] = {"error": f"{exc!r}"}
        import gc

        gc.collect()  # drop the probe's device buffers before serve
        PARTIAL_RESULT = result
        # The axon relay's compile endpoint can drop transiently mid-session
        # (seen r3: UNAVAILABLE .../remote_compile after the kernels leg);
        # one backoff-retry rescues the TTFT number.
        for attempt in range(2):
            try:
                result["detail"]["serve"] = serve_bench_result(backend)
                break
            except Exception as exc:
                result["detail"]["serve"] = {"error": f"{exc!r}",
                                             "attempt": attempt + 1}
                if attempt == 0:
                    time.sleep(30)
    _emit(result)


if __name__ == "__main__":
    import os
    import signal

    def _watchdog(signum, frame):  # backend hang after a healthy probe
        if PARTIAL_RESULT is not None:
            PARTIAL_RESULT.setdefault("detail", {})["partial"] = (
                "late leg hung; emitting measured training result")
            _emit(PARTIAL_RESULT)
        else:
            _emit_error_json("watchdog: bench exceeded wall-clock budget")
        os._exit(0)

    try:
        signal.signal(signal.SIGALRM, _watchdog)
        signal.alarm(3300)
    except (ValueError, AttributeError, OSError):
        pass
    try:
        if "--serve" in sys.argv:
            serve_bench()
        elif "--kernels" in sys.argv:
            kernels_main()
        else:
            main()
    except Exception as exc:  # never exit without a parseable JSON line
        traceback.print_exc()
        _emit_error_json(f"{type(exc).__name__}: {exc}")
        sys.exit(0)
