"""Headline benchmark: flagship Llama training throughput on one TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The reference publishes a scalability envelope, not tokens/sec (BASELINE.md);
the repo's north-star target is Llama-3-8B FSDP at >=45% MFU on v5e. On the
single available chip we run the same training math (fwd+bwd+adamw, bf16,
remat) at a ~1B-parameter configuration and report tokens/sec/chip with
model FLOPs utilization; vs_baseline = achieved_MFU / 0.45 target.
"""

from __future__ import annotations

import json
import sys
import time
import traceback

PEAK_FLOPS = {
    "v5e": 197e12,   # bf16 peak per chip
    "v5p": 459e12,
    "v4": 275e12,
    "cpu": 1e11,     # nominal, keeps the metric finite off-TPU
}


def detect_peak() -> float:
    import jax

    if jax.default_backend() != "tpu":
        return PEAK_FLOPS["cpu"]
    kind = jax.devices()[0].device_kind.lower()
    for name, peak in PEAK_FLOPS.items():
        if name in kind.replace(" ", ""):
            return peak
    return PEAK_FLOPS["v5e"]


def init_backend(retries: int = 3, backoff_s: float = 10.0,
                 probe_timeout_s: float = 150.0) -> str:
    """Bring up the jax backend robustly.

    Round-1 failure modes: the TPU plugin raised once (unhandled) OR hung
    indefinitely during init.  Neither is recoverable in-process, so we probe
    it in a SUBPROCESS with a timeout + retries/backoff; on persistent
    failure we force the CPU platform before importing jax here, so the
    benchmark always produces a JSON line.

    Returns the platform the parent should use ("tpu" or "cpu")."""
    import os
    import subprocess

    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        return "cpu"
    for attempt in range(retries):
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print('PLATFORM=' + jax.default_backend())"],
                capture_output=True, text=True, timeout=probe_timeout_s)
            platform = None
            for line in r.stdout.splitlines():
                if line.startswith("PLATFORM="):
                    platform = line.split("=", 1)[1]
            if platform == "tpu":
                return "tpu"
            if r.returncode == 0 and platform is not None:
                # Clean probe, no TPU plugin: a definitive answer — don't
                # burn retries/backoff re-asking it.
                break
            print(f"bench: probe {attempt + 1}/{retries} got non-tpu "
                  f"backend (rc={r.returncode}); stderr tail: "
                  f"{r.stderr[-300:]}", file=sys.stderr)
        except subprocess.TimeoutExpired:
            print(f"bench: probe {attempt + 1}/{retries} timed out after "
                  f"{probe_timeout_s}s", file=sys.stderr)
        if attempt < retries - 1:
            time.sleep(backoff_s * (1.5 ** attempt))
    print("bench: TPU backend unavailable; falling back to CPU",
          file=sys.stderr)
    # Env vars alone are NOT enough: the host's sitecustomize may have
    # already imported jax with the TPU plugin registered, in which case
    # main()'s first jax call would still initialize (and hang on) the
    # broken backend. Force the in-process config too.
    from __graft_entry__ import _force_cpu_platform

    _force_cpu_platform(1)
    return "cpu"


def _emit_error_json(msg: str) -> None:
    print(json.dumps({
        "metric": "llama1b_train_tokens_per_sec_per_chip",
        "value": 0,
        "unit": "tokens/s",
        "vs_baseline": 0,
        "detail": {"error": msg},
    }), flush=True)


def serve_bench():
    """Secondary probe (`python bench.py --serve`): serving TTFT + decode
    throughput on one chip via the native paged engine (north star: 8B
    <150ms p50 TTFT on v5e; scaled-down model on the single dev chip)."""
    backend = init_backend()
    import numpy as np

    import jax

    from ray_tpu.llm.engine import LLMEngine
    from ray_tpu.llm.model_runner import ModelRunner
    from ray_tpu.llm.sampling import SamplingParams
    from ray_tpu.models import llama

    on_tpu = backend == "tpu"
    if on_tpu:
        # ~1.9B-param llama (hd=128 so the Pallas kernel engages) in bf16.
        config = llama.LlamaConfig(
            vocab_size=32000, d_model=2048, n_layers=18, n_heads=16,
            n_kv_heads=8, d_ff=8192, max_seq=2048)
        num_blocks, prompt_len, gen_tokens, n_requests = 1024, 512, 64, 8
    else:
        config = llama.LlamaConfig.tiny(max_seq=128)
        num_blocks, prompt_len, gen_tokens, n_requests = 64, 48, 8, 3

    params = llama.init_params(config, jax.random.key(0))
    runner = ModelRunner(config, params, num_blocks=num_blocks,
                         block_size=16, chunk_size=512 if on_tpu else 16)
    engine = LLMEngine(runner, max_batch_size=8,
                       prefill_chunk=512 if on_tpu else 16,
                       pipeline_depth=8)
    rng = np.random.RandomState(0)
    prompt = rng.randint(1, config.vocab_size, prompt_len).tolist()

    # Warmup: compile the prefill + decode buckets.
    engine.generate([prompt], SamplingParams(max_tokens=4))

    ttfts, decode_times, decoded = [], [], 0
    for _ in range(n_requests):
        p = rng.randint(1, config.vocab_size, prompt_len).tolist()
        t0 = time.perf_counter()
        first_at = None
        for i, _tok in enumerate(engine.stream(
                p, SamplingParams(max_tokens=gen_tokens))):
            if i == 0:
                first_at = time.perf_counter() - t0
        total = time.perf_counter() - t0
        ttfts.append(first_at)
        decode_times.append(total - first_at)
        decoded += gen_tokens - 1
    p50 = sorted(ttfts)[len(ttfts) // 2]
    decode_tok_s = decoded / max(sum(decode_times), 1e-9)
    print(json.dumps({
        "metric": "llm_serve_ttft_p50_ms",
        "value": round(p50 * 1000, 2),
        "unit": "ms",
        "vs_baseline": round(0.150 / max(p50, 1e-9), 3),  # >1 = beats target
        "detail": {
            "prompt_len": prompt_len,
            "decode_tokens_per_sec": round(decode_tok_s, 1),
            "gen_tokens": gen_tokens,
            "requests": n_requests,
            "attention_impl": runner.attention_impl,
            "params_b": round(config.num_params() / 1e9, 3),
            "backend": jax.default_backend(),
        },
    }))


def main():
    backend = init_backend()
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models import llama

    on_tpu = backend == "tpu"
    if on_tpu:
        # ~0.9B params: fits one 16GB v5e chip with bf16 params + adam
        # moments (mu bf16, nu fp32) + remat'd activations.
        config = llama.LlamaConfig(
            vocab_size=32000, d_model=2048, n_layers=14, n_heads=16,
            n_kv_heads=8, d_ff=7168, max_seq=2048)
        batch, seq, steps = 4, 2048, 10
    else:  # smoke path for dev machines
        config = llama.LlamaConfig.tiny(max_seq=128)
        batch, seq, steps = 4, 128, 3

    opt = optax.adamw(1e-4, b1=0.9, b2=0.95,
                      mu_dtype=jnp.bfloat16)

    @jax.jit
    def init_state(key):
        params = llama.init_params(config, key)
        return {"params": params, "opt": opt.init(params)}

    from functools import partial

    @partial(jax.jit, donate_argnums=(0,))
    def train_step(state, tokens):
        def loss(p):
            l, m = llama.loss_fn(p, {"tokens": tokens}, config)
            return l

        l, grads = jax.value_and_grad(loss)(state["params"])
        updates, opt_state = opt.update(grads, state["opt"], state["params"])
        params = optax.apply_updates(state["params"], updates)
        return {"params": params, "opt": opt_state}, l

    state = init_state(jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (batch, seq + 1), 0,
                                config.vocab_size)

    # Warmup / compile. Sync via explicit scalar fetch: block_until_ready can
    # be a no-op on remote-execution PJRT backends, so every timing boundary
    # forces a device->host value transfer.
    state, l = train_step(state, tokens)
    _ = float(l)
    state, l = train_step(state, tokens)
    _ = float(l)

    t0 = time.perf_counter()
    for _ in range(steps):
        state, l = train_step(state, tokens)
    final_loss = float(l)  # forces completion of the whole chain
    dt = time.perf_counter() - t0

    tokens_per_step = batch * seq
    tok_s = tokens_per_step * steps / dt
    flops_per_token = config.flops_per_token(seq)
    mfu = tok_s * flops_per_token / detect_peak()

    print(json.dumps({
        "metric": "llama1b_train_tokens_per_sec_per_chip",
        "value": round(tok_s, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.45, 4),
        "detail": {
            "mfu": round(mfu, 4),
            "params_b": round(config.num_params() / 1e9, 3),
            "batch_tokens": tokens_per_step,
            "steps": steps,
            "backend": jax.default_backend(),
            "loss": final_loss,
        },
    }))


if __name__ == "__main__":
    import os
    import signal

    def _watchdog(signum, frame):  # backend hang after a healthy probe
        _emit_error_json("watchdog: bench exceeded 900s wall clock")
        os._exit(0)

    try:
        signal.signal(signal.SIGALRM, _watchdog)
        signal.alarm(900)
    except (ValueError, AttributeError, OSError):
        pass
    try:
        if "--serve" in sys.argv:
            serve_bench()
        else:
            main()
    except Exception as exc:  # never exit without a parseable JSON line
        traceback.print_exc()
        _emit_error_json(f"{type(exc).__name__}: {exc}")
        sys.exit(0)
